"""Multi-replica router (serve/router.py, ISSUE 11): load-aware
placement, per-replica shedding, health drop/recovery, failover,
autoscale signals, and the tier-1 pinned zero-error rolling deploy."""
import json
import threading
import time
import urllib.request

import jax
import numpy as np
import pytest

from alpa_tpu import fault
from alpa_tpu.checkpoint.manager import CheckpointManager
from alpa_tpu.global_env import global_config
from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
from alpa_tpu.serve.controller import Controller
from alpa_tpu.serve.router import (LocalReplicaHandle, Router,
                                   RouterServer)


def _tiny(**gen_kwargs):
    from alpa_tpu.serve.generation import Generator
    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                    seq_len=32, vocab_size=64)
    model, params = init_gpt_real(cfg, 1)
    return Generator(model, params, cfg, **gen_kwargs), model, params, cfg


PROMPT = [3, 1, 4, 1, 5]
REQ = {"model": "m", "prompt_ids": PROMPT, "max_new_tokens": 4}


class StubHandle:
    """Scriptable replica: configurable load report, health, and
    completion behavior."""

    def __init__(self, load=None, health_code=200, fail_with=None):
        self.load_report = load or {"queue_depth": 0,
                                    "tokens_in_flight": 0,
                                    "ttft_p99_ms": None}
        self.health_code = health_code
        self.fail_with = fail_with
        self.calls = 0

    def completions(self, request):
        self.calls += 1
        if self.fail_with is not None:
            raise self.fail_with
        return {"output_ids": [request["prompt_ids"] + [0]]}

    def completions_stream(self, request):
        self.calls += 1
        if self.fail_with is not None:
            raise self.fail_with
        return iter([0, 1])

    def healthz(self):
        return self.health_code, {"load": self.load_report}

    def load(self):
        return self.load_report

    def reload(self, model, ckpt_dir, step=None):
        return {"model": model, "step": step}


class TestPlacement:

    def test_least_loaded_prefers_idle_replica(self):
        r = Router(policy="least_loaded")
        busy = StubHandle(load={"queue_depth": 10,
                                "tokens_in_flight": 500,
                                "ttft_p99_ms": 50.0})
        idle = StubHandle()
        r.add_replica("busy", busy)
        r.add_replica("idle", idle)
        for _ in range(8):
            r.submit(dict(REQ))
        assert idle.calls == 8 and busy.calls == 0

    def test_round_robin_rotates(self):
        r = Router(policy="round_robin")
        a, b = StubHandle(), StubHandle()
        r.add_replica("a", a)
        r.add_replica("b", b)
        for _ in range(8):
            r.submit(dict(REQ))
        assert a.calls == 4 and b.calls == 4

    def test_unknown_policy_rejected(self):
        with pytest.raises(ValueError):
            Router(policy="coin_flip")


class TestSheddingAndHealth:

    def test_saturated_replica_routed_around(self):
        r = Router(policy="least_loaded", shed_queue_depth=4)
        sat = StubHandle(load={"queue_depth": 50,
                               "tokens_in_flight": 0,
                               "ttft_p99_ms": None})
        ok = StubHandle()
        r.add_replica("sat", sat)
        r.add_replica("ok", ok)
        for _ in range(4):
            r.submit(dict(REQ))
        assert ok.calls == 4 and sat.calls == 0

    def test_503_only_when_every_replica_saturated(self):
        r = Router(policy="least_loaded", shed_queue_depth=4)
        load = {"queue_depth": 50, "tokens_in_flight": 0,
                "ttft_p99_ms": None}
        r.add_replica("a", StubHandle(load=dict(load)))
        r.add_replica("b", StubHandle(load=dict(load)))
        with pytest.raises(fault.ServiceDegradedError):
            r.submit(dict(REQ))
        assert r.sheds == 1

    def test_shed_then_admit(self):
        r = Router(policy="least_loaded", shed_queue_depth=4)
        st = StubHandle(load={"queue_depth": 50, "tokens_in_flight": 0,
                              "ttft_p99_ms": None})
        r.add_replica("a", st)
        with pytest.raises(fault.ServiceDegradedError):
            r.submit(dict(REQ))
        st.load_report = {"queue_depth": 0, "tokens_in_flight": 0,
                          "ttft_p99_ms": None}
        out = r.submit(dict(REQ))
        assert out["output_ids"][0][-1] == 0

    def test_replica_shed_fails_over_not_503(self):
        """A replica raising ServiceDegradedError (its own shedding)
        only excludes THAT replica."""
        r = Router(policy="round_robin")
        shedding = StubHandle(
            fail_with=fault.ServiceDegradedError("backend down"))
        ok = StubHandle()
        r.add_replica("shedding", shedding)
        r.add_replica("ok", ok)
        for _ in range(4):
            r.submit(dict(REQ))
        assert ok.calls == 4

    def test_degraded_replica_dropped_then_recovers(self):
        r = Router(health_fail_threshold=3)
        flaky = StubHandle(health_code=503)
        ok = StubHandle()
        r.add_replica("flaky", flaky)
        r.add_replica("ok", ok)
        for i in range(3):
            health = r.probe()
            # dropped only after the 3rd consecutive failure
            assert health["flaky"] is (i < 2)
        snap = r.snapshot()
        assert snap["replicas"]["flaky"]["healthy"] is False
        r.submit(dict(REQ))
        assert ok.calls == 1 and flaky.calls == 0
        # one clean probe restores
        flaky.health_code = 200
        assert r.probe()["flaky"] is True
        assert r.snapshot()["replicas"]["flaky"]["healthy"] is True

    def test_transport_error_fails_over_and_counts(self):
        r = Router(health_fail_threshold=1)
        dead = StubHandle(fail_with=ConnectionRefusedError("down"))
        ok = StubHandle()
        r.add_replica("dead", dead)
        r.add_replica("ok", ok)
        out = r.submit(dict(REQ))      # fails over transparently
        assert out["output_ids"][0][-1] == 0
        assert r.snapshot()["replicas"]["dead"]["healthy"] is False

    def test_request_level_error_propagates(self):
        """Client mistakes (unknown model, ...) must NOT burn through
        every replica."""
        r = Router()
        bad = StubHandle(fail_with=KeyError("unknown model"))
        other = StubHandle()
        r.add_replica("bad", bad)
        r.add_replica("other", other)
        hit = 0
        for _ in range(4):
            try:
                r.submit(dict(REQ))
            except KeyError:
                hit += 1
        assert bad.calls + other.calls == 4
        assert hit == bad.calls          # bad's errors propagated


class TestAutoscale:

    def test_sustained_high_fires_want_more_once_per_window(self):
        now = [1000.0]
        r = Router(autoscale_window_s=10.0, autoscale_hi_queue=4.0,
                   autoscale_lo_queue=1.0, clock=lambda: now[0])
        fired = []
        r.on_want_more = lambda router, mean: fired.append(mean)
        for _ in range(12):              # 12 samples over 11s, depth 8
            r._as_samples.append((now[0], 8.0))
            assert r.evaluate_autoscale() in (None, "want_more")
            now[0] += 1.0
        assert r.want_more_signals == 1
        assert fired and fired[0] == 8.0
        # stays high: next signal only after another full window
        for _ in range(12):
            r._as_samples.append((now[0], 8.0))
            r.evaluate_autoscale()
            now[0] += 1.0
        assert r.want_more_signals == 2

    def test_sustained_low_fires_want_fewer(self):
        now = [0.0]
        r = Router(autoscale_window_s=10.0, autoscale_hi_queue=4.0,
                   autoscale_lo_queue=1.0, clock=lambda: now[0])
        for _ in range(12):
            r._as_samples.append((now[0], 0.2))
            r.evaluate_autoscale()
            now[0] += 1.0
        assert r.want_fewer_signals == 1

    def test_mixed_window_fires_nothing(self):
        now = [0.0]
        r = Router(autoscale_window_s=10.0, autoscale_hi_queue=4.0,
                   autoscale_lo_queue=1.0, clock=lambda: now[0])
        for i in range(12):
            r._as_samples.append((now[0], 8.0 if i % 2 else 0.2))
            r.evaluate_autoscale()
            now[0] += 1.0
        assert r.want_more_signals == 0
        assert r.want_fewer_signals == 0


def _save_ckpt(tmp_path, params, step=1):
    ma = CheckpointManager(str(tmp_path / "ckpt"), async_save=False)
    ma.save(step, params)
    ma.wait()
    return str(tmp_path / "ckpt")


def _two_controller_router(**router_kwargs):
    ctrls, gens = [], []
    for _ in range(2):
        gen, model, params, cfg = _tiny()
        ctrl = Controller()
        ctrl.register_model("m", gen)
        ctrls.append(ctrl)
        gens.append((model, params, cfg))
    r = Router(policy="least_loaded", **router_kwargs)
    r.add_replica("r0", LocalReplicaHandle(ctrls[0]))
    r.add_replica("r1", LocalReplicaHandle(ctrls[1]))
    return r, ctrls, gens


class TestRollingDeploy:
    """Tier-1 pinned: rolling reload across 2 live replicas under
    hammering traffic (batched + streamed) produces ZERO failed
    requests, and both replicas serve the new weights afterwards."""

    def test_rolling_reload_zero_errors(self, tmp_path):
        r, ctrls, gens = _two_controller_router()
        model, params, cfg = gens[0]
        new_params = jax.tree_util.tree_map(lambda x: x * 0.5 + 0.25,
                                            params)
        ckpt_dir = _save_ckpt(tmp_path, new_params)

        errors, outputs, stream_errors = [], [], []
        stop = threading.Event()

        def hammer():
            while not stop.is_set():
                try:
                    out = r.submit({"model": "m", "prompt_ids": PROMPT,
                                    "max_new_tokens": 4})
                    outputs.append(out["output_ids"][0])
                except Exception as e:  # pylint: disable=broad-except
                    errors.append(e)
                    return

        def hammer_stream():
            while not stop.is_set():
                try:
                    it = r.submit_stream(
                        {"model": "m", "prompt_ids": PROMPT,
                         "max_new_tokens": 4})
                    toks = list(it)
                    assert len(toks) == 4
                except Exception as e:  # pylint: disable=broad-except
                    stream_errors.append(e)
                    return

        threads = ([threading.Thread(target=hammer) for _ in range(2)]
                   + [threading.Thread(target=hammer_stream)])
        for t in threads:
            t.start()
        try:
            time.sleep(0.3)
            results = r.rolling_reload("m", ckpt_dir)
            time.sleep(0.3)
        finally:
            stop.set()
            for t in threads:
                t.join()

        assert not errors, f"batched requests failed: {errors}"
        assert not stream_errors, f"streams failed: {stream_errors}"
        assert [res["replica"] for res in results] == ["r0", "r1"]
        assert outputs
        # after the deploy BOTH replicas answer with the new weights
        from alpa_tpu.serve.generation import (GenerationConfig,
                                               Generator)
        want_new = np.asarray(Generator(model, new_params, cfg)
                              .generate(np.array([PROMPT], np.int32),
                                        GenerationConfig(
                                            max_new_tokens=4)))[0]
        for ctrl in ctrls:
            out = ctrl.completions({"model": "m", "prompt_ids": PROMPT,
                                    "max_new_tokens": 4})
            assert out["output_ids"][0] == want_new.tolist()

    def test_draining_replica_not_picked(self):
        r = Router()
        a, b = StubHandle(), StubHandle()
        r.add_replica("a", a)
        r.add_replica("b", b)
        r._replicas["a"].draining = True
        for _ in range(4):
            r.submit(dict(REQ))
        assert b.calls == 4 and a.calls == 0


class TestRouterServer:

    def test_healthz_metrics_completions(self):
        gen, _model, _params, _cfg = _tiny()
        ctrl = Controller()
        ctrl.register_model("m", gen)
        r = Router()
        r.add_replica("r0", LocalReplicaHandle(ctrl))
        server = RouterServer(r, port=0)
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            with urllib.request.urlopen(base + "/healthz") as resp:
                body = json.loads(resp.read())
                assert resp.status == 200
            assert body["status"] == "ok"
            assert body["replicas"]["r0"]["healthy"] is True

            req = urllib.request.Request(
                base + "/completions",
                data=json.dumps(REQ).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req) as resp:
                out = json.loads(resp.read())
            assert len(out["output_ids"][0]) == len(PROMPT) + 4

            with urllib.request.urlopen(base + "/metrics") as resp:
                text = resp.read().decode()
            for family in ("alpa_router_requests_total",
                           "alpa_router_replica_queue_depth",
                           "alpa_kv_blocks_in_use",
                           "alpa_kv_prefix_hits_total",
                           "alpa_kv_bytes_saved_total"):
                assert family in text, f"missing metric {family}"
        finally:
            server.shutdown()

    def test_healthz_503_when_no_replica_routable(self):
        r = Router()
        server = RouterServer(r, port=0)
        server.start()
        base = f"http://127.0.0.1:{server.port}"
        try:
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(base + "/healthz")
            assert exc.value.code == 503
        finally:
            server.shutdown()


class TestPagedControllerRegistration:
    """ISSUE 11 satellite: under kv_paged + kv_prefix_reuse, registered
    prefixes become pre-warmed paged-index entries — replicas of one
    model may register DIFFERENT prefixes (the old same-prefix error is
    gone), and requests send full prompts."""

    def test_different_prefixes_coexist(self, monkeypatch):
        monkeypatch.setattr(global_config, "kv_paged", True)
        monkeypatch.setattr(global_config, "kv_prefix_reuse", True)
        monkeypatch.setattr(global_config, "kv_block_size", 8)
        ctrl = Controller()
        gen_a, _m, _p, _c = _tiny(prefill_chunk=8)
        gen_b, _m, _p, _c = _tiny(prefill_chunk=8)
        pre_a = list(range(1, 9))
        pre_b = list(range(11, 19))
        ctrl.register_model("m", gen_a, prefix_ids=pre_a)
        # old behavior raised on a mismatched second prefix; paged
        # supersession accepts it
        ctrl.register_model("m", gen_b, prefix_ids=pre_b)
        out = ctrl.completions({"model": "m",
                                "prompt_ids": pre_a + [30, 31],
                                "max_new_tokens": 3})
        assert len(out["output_ids"][0]) == len(pre_a) + 2 + 3

    def test_legacy_same_prefix_rule_kept_when_reuse_off(self,
                                                         monkeypatch):
        monkeypatch.setattr(global_config, "kv_paged", False)
        ctrl = Controller()
        gen_a, _m, _p, _c = _tiny(prefill_chunk=8)
        gen_b, _m, _p, _c = _tiny(prefill_chunk=8)
        ctrl.register_model("m", gen_a, prefix_ids=[1, 2, 3])
        with pytest.raises(ValueError):
            ctrl.register_model("m", gen_b, prefix_ids=[4, 5, 6])
