"""Disaggregated prefill/decode serving (serve/disagg.py, ISSUE 18).

Tier-1 pinned invariants:
* the disaggregated decode stream is BIT-EXACT (np.array_equal) vs the
  monolithic ContinuousBatchingEngine on the miss, full-hit, and
  shared-prefix paths;
* ``disagg_mode=off`` routes byte-identically to the monolithic path;
* no handoff is ever dropped: a decode-replica death (at ingest or
  mid-stream) re-ingests the retained artifact on a survivor with
  bitwise-identical output, and a corrupt artifact (flipped block hash)
  is rejected + re-fetched — never silently decoded.
"""
import threading
import time

import numpy as np
import pytest

from alpa_tpu import fault
from alpa_tpu.global_env import global_config
from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
from alpa_tpu.serve import disagg
from alpa_tpu.serve.controller import Controller
from alpa_tpu.serve.engine import ContinuousBatchingEngine
from alpa_tpu.serve.generation import GenerationConfig, Generator
from alpa_tpu.serve.kv_cache import KVBlockPool
from alpa_tpu.serve.router import LocalReplicaHandle, Router

BS = 8

PROMPT = np.array([5, 9, 3, 7, 1, 2, 8, 4, 6, 11, 13, 2], np.int32)
GCFG = GenerationConfig(max_new_tokens=6, temperature=0.0)
REQ = {"model": "m", "prompt_ids": PROMPT.tolist(),
       "max_new_tokens": 6, "temperature": 0.0}


@pytest.fixture(scope="module")
def tiny():
    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                    seq_len=64, vocab_size=64)
    model, params = init_gpt_real(cfg, 1)
    return model, params, cfg


def _gen(tiny):
    model, params, cfg = tiny
    return Generator(model, params, cfg, prefill_chunk=BS)


def _paged_engine(tiny, block_size=BS):
    gen = _gen(tiny)
    pool = KVBlockPool.for_generator(gen, max_batch=2,
                                     block_size=block_size)
    return ContinuousBatchingEngine(gen, max_batch=2, kv_pool=pool)


@pytest.fixture
def paged(tiny):
    global_config.kv_paged, prev_p = True, global_config.kv_paged
    global_config.kv_prefix_reuse, prev_r = \
        True, global_config.kv_prefix_reuse
    yield
    global_config.kv_paged = prev_p
    global_config.kv_prefix_reuse = prev_r


class TestArtifact:
    """Wire-format + content-hash mechanics (no engines)."""

    def _artifact(self, tiny, codec="off"):
        gen = _gen(tiny)
        pe = disagg.PrefillEngine(gen, model="m", codec=codec)
        try:
            return pe.prefill(PROMPT, GCFG)
        finally:
            pe.shutdown()

    def test_wire_roundtrip_identical(self, tiny):
        art = self._artifact(tiny)
        back = disagg.KVHandoffArtifact.from_wire(art.to_wire())
        assert back.request_id == art.request_id
        assert np.array_equal(back.prompt, art.prompt)
        assert np.array_equal(back.last_logits, art.last_logits)
        for lay_a, lay_b in zip(art.layers, back.layers):
            for key in lay_a:
                assert np.array_equal(lay_a[key], lay_b[key])
        assert back.block_hashes == art.block_hashes
        # deterministic wire: re-fetching serializes identical bytes
        assert art.to_wire() == back.to_wire()

    def test_flipped_block_hash_rejected(self, tiny):
        wire = self._artifact(tiny).to_wire()
        wire["block_hashes"][0] = "0" * 64
        with pytest.raises(disagg.ArtifactCorruptError):
            disagg.KVHandoffArtifact.from_wire(wire)

    def test_corrupt_payload_rejected(self, tiny):
        wire = self._artifact(tiny).to_wire()
        data = wire["layers"][0]["k"]["data"]
        wire["layers"][0]["k"]["data"] = \
            ("A" if data[0] != "A" else "B") + data[1:]
        with pytest.raises(disagg.ArtifactCorruptError):
            disagg.KVHandoffArtifact.from_wire(wire)

    def test_malformed_wire_rejected(self, tiny):
        wire = self._artifact(tiny).to_wire()
        del wire["layers"]
        with pytest.raises(disagg.ArtifactCorruptError):
            disagg.KVHandoffArtifact.from_wire(wire)

    def test_codec_int8_roundtrip_within_bound(self, tiny):
        from alpa_tpu.pipeline_parallel import reshard_codec
        raw = self._artifact(tiny, codec="off")
        art = self._artifact(tiny, codec="int8")
        assert "k_q" in art.layers[0]
        back = disagg.KVHandoffArtifact.from_wire(art.to_wire())
        for l, lay in enumerate(raw.layers):
            tail = lay["k"].shape[2:]
            kq, _vq = back.dense_rows(l, tail)
            kraw = lay["k"].reshape((-1,) + tail)
            scale = np.abs(kraw).max() or 1.0
            err = np.abs(kq - kraw).max() / scale
            assert err <= reshard_codec.ERROR_BOUND["int8"] * 4
        # quantized payload is hashed over the wire form: verify holds
        back.verify()


class TestBitExact:
    """Pinned: disagg decode == monolithic engine, all reuse paths."""

    def test_miss_fullhit_shared_prefix(self, tiny):
        mono = _paged_engine(tiny)
        dec = _paged_engine(tiny)
        gen = _gen(tiny)
        # block_size 4 so the 8-token shared prefix spans full blocks
        # and the LATER prefills really take the gather + chunked-suffix
        # hit path (block_size 16 would round every match down to zero)
        pool = KVBlockPool.for_generator(gen, block_size=4,
                                         prefix_reuse=True)
        pe = disagg.PrefillEngine(gen, model="m", kv_pool=pool,
                                  prompt_bucket=gen.prompt_buckets[-1])
        try:
            p2 = np.concatenate(
                [PROMPT[:8], np.array([21, 22, 23, 24], np.int32)])
            for label, p in (("miss", PROMPT),
                             ("shared-prefix", p2),
                             ("full-hit", PROMPT)):
                ref = mono.submit(p, GCFG)
                art = disagg.KVHandoffArtifact.from_wire(
                    pe.prefill(p, GCFG).to_wire())
                out = disagg.ingest(dec, art)
                assert np.array_equal(np.asarray(ref),
                                      np.asarray(out)), label
        finally:
            pe.shutdown()
            mono.shutdown()
            dec.shutdown()

    def test_prefill_side_prefix_hits_accumulate(self, tiny):
        gen = _gen(tiny)
        pool = KVBlockPool.for_generator(gen, block_size=4,
                                         prefix_reuse=True)
        pe = disagg.PrefillEngine(gen, model="m", kv_pool=pool)
        try:
            pe.prefill(PROMPT, GCFG)
            before = pe.pool.stats()["prefix_hits"]
            pe.prefill(PROMPT, GCFG)
            assert pe.pool.stats()["prefix_hits"] == before + 1
        finally:
            pe.shutdown()

    def test_decode_side_registers_prefix_chain(self, tiny):
        """Ingest must register the prompt chain in the DECODE pool so
        later monolithic submits on that replica still hit."""
        dec = _paged_engine(tiny)
        gen = _gen(tiny)
        pe = disagg.PrefillEngine(gen, model="m")
        try:
            disagg.ingest(dec, pe.prefill(PROMPT, GCFG))
            hits_before = dec._pool.stats()["prefix_hits"]
            dec.submit(PROMPT, GCFG)
            assert dec._pool.stats()["prefix_hits"] > hits_before
        finally:
            pe.shutdown()
            dec.shutdown()


def _fleet(tiny, n_decode=2, **router_kw):
    """1 prefill + n decode controllers behind a phase-aware router."""
    cp = Controller()
    cp.register_model("m", _gen(tiny))
    r = Router(disagg_mode="auto", **router_kw)
    r.add_replica("p0", LocalReplicaHandle(cp), phase="prefill")
    decs = []
    for i in range(n_decode):
        cd = Controller()
        cd.register_model("m", _gen(tiny))
        r.add_replica(f"d{i}", LocalReplicaHandle(cd), phase="decode")
        decs.append(cd)
    return r, cp, decs


class TestRouterDisagg:

    def test_router_disagg_matches_monolithic(self, tiny, paged):
        c0 = Controller()
        c0.register_model("m", _gen(tiny))
        r0 = Router(disagg_mode="off")
        r0.add_replica("solo", LocalReplicaHandle(c0))
        ref = r0.submit(dict(REQ))

        r, _cp, _ = _fleet(tiny)
        assert r.snapshot()["disagg"]["active"]
        out = r.submit(dict(REQ))
        assert out == ref
        assert r.disagg_handoffs == 1

    def test_mode_off_is_monolithic_path(self, tiny, paged):
        """disagg_mode=off never touches the disagg path even with
        phased replicas present: handoff counters stay zero and phased
        pools are simply ignored for placement filtering."""
        c0 = Controller()
        c0.register_model("m", _gen(tiny))
        r = Router(disagg_mode="off")
        r.add_replica("a", LocalReplicaHandle(c0), phase="prefill")
        assert not r._disagg_active()
        out = r.submit(dict(REQ))
        assert out["output_ids"][0][:len(PROMPT)] == PROMPT.tolist()
        assert r.disagg_handoffs == 0
        assert r.snapshot()["disagg"]["active"] is False

    def test_auto_needs_both_pools(self, tiny, paged):
        c0 = Controller()
        c0.register_model("m", _gen(tiny))
        r = Router(disagg_mode="auto")
        r.add_replica("p0", LocalReplicaHandle(c0), phase="prefill")
        assert not r._disagg_active()  # no decode pool yet

    def test_ack_releases_retained_artifact(self, tiny, paged):
        r, cp, _ = _fleet(tiny)
        r.submit(dict(REQ))
        pe = cp._models["m"][0]._prefill_engine
        with pe._cv:
            assert len(pe._retained) == 0, \
                "clean stream end must ack the retained artifact"

    def test_backpressure_throttles_prefill_admission(self, tiny,
                                                      paged):
        r, _cp, _ = _fleet(tiny, disagg_backpressure_depth=1)
        # inflate the decode pool's apparent backlog
        for name in ("d0", "d1"):
            r._replicas[name].inflight = 5
        with pytest.raises(fault.ServiceDegradedError,
                           match="backpressure"):
            r.submit(dict(REQ))
        assert r.disagg_backpressure_sheds == 1
        # backlog clears -> admission resumes
        for name in ("d0", "d1"):
            r._replicas[name].inflight = 0
        assert r.submit(dict(REQ))["output_ids"]


class TestFailover:
    """No handoff is ever dropped (ISSUE 18 satellite 4)."""

    def test_decode_death_at_ingest_reingests_bitexact(self, tiny,
                                                       paged):
        ref_r, _cp0, _ = _fleet(tiny)
        ref = ref_r.submit(dict(REQ))

        r, _cp, _decs = _fleet(tiny)
        st = r._replicas["d0"]
        real = st.handle

        class DeadIngest:
            def __getattr__(self, k):
                if k == "ingest":
                    def boom(wire):
                        raise ConnectionError("decode replica down")
                    return boom
                return getattr(real, k)
        st.handle = DeadIngest()
        out = r.submit(dict(REQ))
        assert out == ref, "re-ingested output must be bit-identical"
        assert r.disagg_reingests == 1
        assert st.fails == 1, "dead replica is health-counted"

    def test_decode_death_mid_stream_reingests_bitexact(self, tiny,
                                                        paged):
        ref_r, _cp0, _ = _fleet(tiny)
        ref = ref_r.submit(dict(REQ))["output_ids"][0]

        r, _cp, _decs = _fleet(tiny)
        stream = r.submit_stream(dict(REQ, stream=True))
        toks = [next(stream), next(stream)]

        class DyingIter:
            def __next__(self):
                raise ConnectionError("decode died mid-stream")

            def __iter__(self):
                return self

            def close(self):
                pass
        died = stream._dst.name
        stream._inner = DyingIter()
        toks.extend(stream)
        assert PROMPT.tolist() + toks == ref
        assert r.disagg_reingests == 1
        assert stream._dst.name != died, "stream moved to a survivor"

    def test_corrupt_artifact_refetched_never_decoded(self, tiny,
                                                      paged):
        ref_r, _cp0, _ = _fleet(tiny)
        ref = ref_r.submit(dict(REQ))

        r, _cp, _decs = _fleet(tiny, n_decode=1)
        st = r._replicas["d0"]
        real = st.handle
        flips = {"n": 0}

        class CorruptingWire:
            """Flip a block hash on the first wire copy only — models
            one-shot transport corruption."""

            def __getattr__(self, k):
                if k == "ingest":
                    def ingest(wire):
                        if flips["n"] == 0:
                            flips["n"] += 1
                            wire = dict(wire,
                                        block_hashes=["f" * 64] +
                                        wire["block_hashes"][1:])
                        return real.ingest(wire)
                    return ingest
                return getattr(real, k)
        st.handle = CorruptingWire()
        out = r.submit(dict(REQ))
        assert out == ref, "re-fetched artifact must decode bit-exact"
        assert flips["n"] == 1
        assert r.disagg_reingests == 1

    def test_sampled_stream_propagates_decode_death(self, tiny, paged):
        """do_sample streams cannot replay deterministically — the
        failure surfaces instead of silently diverging."""
        r, _cp, _decs = _fleet(tiny)
        req = dict(REQ, stream=True, do_sample=True, temperature=0.7)
        stream = r.submit_stream(req)
        next(stream)

        class DyingIter:
            def __next__(self):
                raise ConnectionError("boom")

            def __iter__(self):
                return self

            def close(self):
                pass
        stream._inner = DyingIter()
        with pytest.raises(ConnectionError):
            list(stream)


class TestFairness:
    """ISSUE 18 satellite 3: a tenant's WFQ weight holds on the
    disaggregated prefill pool — a flooding tenant cannot starve
    another tenant's admission (and therefore its decode SLO)."""

    def test_weighted_tenant_jumps_flooded_queue(self, tiny):
        from alpa_tpu.serve.scheduler import WeightedFairQueue
        gen = _gen(tiny)
        pe = disagg.PrefillEngine(
            gen, model="m",
            scheduler=WeightedFairQueue({"paid": 8, "flood": 1}))
        order = []
        lock = threading.Lock()
        gate = threading.Event()
        in_first = threading.Event()
        orig = pe._prefill_one

        def gated(item):
            # park the worker inside the FIRST request so the flood and
            # the paid request pile up behind it deterministically
            if not in_first.is_set():
                in_first.set()
                gate.wait(timeout=60)
            return orig(item)
        pe._prefill_one = gated

        def one(tenant, i):
            pe.prefill(PROMPT, GCFG, queue=tenant,
                       request_id=f"{tenant}-{i}")
            with lock:
                order.append(tenant)
        try:
            hold = threading.Thread(target=one, args=("flood", 99))
            hold.start()
            assert in_first.wait(timeout=60)
            threads = [threading.Thread(target=one, args=("flood", i))
                       for i in range(6)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + 60
            while pe.queue_depth() < 6:
                assert time.monotonic() < deadline, pe.queue_depth()
                time.sleep(0.001)
            paid = threading.Thread(target=one, args=("paid", 0))
            paid.start()
            while pe.queue_depth() < 7:
                assert time.monotonic() < deadline, pe.queue_depth()
                time.sleep(0.001)
            gate.set()
            for t in [hold, paid] + threads:
                t.join(timeout=60)
        finally:
            gate.set()
            pe.shutdown()
        # the paid tenant (weight 8) must not sit behind the whole
        # flood: it completes within the first few slots
        assert "paid" in order
        assert order.index("paid") <= 2, order

    def test_queue_tag_rides_artifact_to_decode_pool(self, tiny):
        gen = _gen(tiny)
        pe = disagg.PrefillEngine(gen, model="m")
        try:
            art = pe.prefill(PROMPT, GCFG, queue="tenant-a")
        finally:
            pe.shutdown()
        wire = art.to_wire()
        assert wire["queue"] == "tenant-a"
        back = disagg.KVHandoffArtifact.from_wire(wire)
        assert back.queue == "tenant-a"
