"""Serving under concurrent load (VERDICT r4 next #5): N simultaneous
HTTP clients — mixed SSE + non-streaming — against the controller +
engine must all succeed, overlap their work (no serialization through
the ThreadingHTTPServer or the engine lock), and keep streaming TTFT
bounded.  The committed artifact (benchmark/results/serving_load.json)
is produced by scripts/serving_load_bench.py with the same harness at
16 clients.
"""
from scripts.serving_load_bench import run_load


def test_concurrent_mixed_load():
    stats = run_load(n_clients=8, n_requests=2, max_new_tokens=6)
    assert stats["errors"] == [], stats
    assert stats["ok"] == 16, stats
    # concurrency: total client-observed time must overlap heavily
    assert stats["sum_of_individual_s"] > 2 * stats["wall_s"], stats
    # streaming stays responsive while the batch path churns (loose
    # bound: CI boxes are noisy; steady-state p99 measures ~0.2s)
    assert stats["sse_ttft_p99_s"] < 5.0, stats
