"""Serving scheduler policies (ref examples/llm_serving/service/
scheduler.py: WeightedRoundRobin / NestedScheduler /
FrontQueueScheduler — here via start-time fair queueing, see
alpa_tpu/serve/scheduler.py).
"""
import numpy as np
import pytest

from alpa_tpu.serve.scheduler import (FIFOQueue, NestedScheduler,
                                      WeightedFairQueue)


def _item(q, i):
    return {"queue": q, "i": i}


class TestWeightedFairQueue:

    def test_backlogged_throughput_follows_weights(self):
        s = WeightedFairQueue({"a": 3.0, "b": 1.0})
        for i in range(200):
            s.append(_item("a", i))
            s.append(_item("b", i))
        first = [s.popleft()["queue"] for _ in range(100)]
        # steady state: 3:1 service ratio (allow boundary slack)
        assert 70 <= first.count("a") <= 80, first.count("a")

    def test_fifo_within_queue(self):
        s = WeightedFairQueue({"a": 2.0, "b": 1.0})
        for i in range(50):
            s.append(_item("a", i))
            s.append(_item("b", i))
        seen = {"a": [], "b": []}
        while len(s):
            it = s.popleft()
            seen[it["queue"]].append(it["i"])
        assert seen["a"] == sorted(seen["a"])
        assert seen["b"] == sorted(seen["b"])

    def test_idle_queue_banks_no_credit(self):
        """A queue that was idle while others drained does not burst
        ahead when it becomes active (its tags start at current vtime)."""
        s = WeightedFairQueue({"a": 1.0, "b": 1.0})
        for i in range(20):
            s.append(_item("a", i))
        for _ in range(20):
            s.popleft()
        # b wakes up; a refills — service should interleave ~1:1, not
        # give b 20 "banked" slots
        for i in range(20):
            s.append(_item("b", i))
            s.append(_item("a", 100 + i))
        first10 = [s.popleft()["queue"] for _ in range(10)]
        assert 3 <= first10.count("b") <= 7, first10

    def test_pushback_goes_first_in_order(self):
        s = WeightedFairQueue()
        for i in range(5):
            s.append(_item("default", i))
        a, b = s.popleft(), s.popleft()
        s.pushback([a, b])
        assert s.popleft() is a and s.popleft() is b

    def test_drain_and_len(self):
        s = WeightedFairQueue({"a": 2.0})
        items = [_item("a", i) for i in range(4)]
        for it in items:
            s.append(it)
        s.pushback([s.popleft()])
        assert len(s) == 4
        assert s.drain() == items
        assert len(s) == 0 and s.peek() is None

    def test_rejects_nonpositive_weights(self):
        with pytest.raises(ValueError):
            WeightedFairQueue({"a": 0.0})


class TestNestedScheduler:

    def test_groups_fair_inner_fifo(self):
        s = NestedScheduler(outer=WeightedFairQueue({"g1": 1.0,
                                                     "g2": 1.0}))
        for i in range(30):
            s.append({"group": "g1", "i": i})
        for i in range(30):
            s.append({"group": "g2", "i": i})
        out = [s.popleft() for _ in range(60)]
        # fair across groups even though g1 enqueued first
        first20 = [o["group"] for o in out[:20]]
        assert 5 <= first20.count("g2") <= 15, first20
        for g in ("g1", "g2"):
            idx = [o["i"] for o in out if o["group"] == g]
            assert idx == sorted(idx)

    def test_protocol_surface(self):
        s = NestedScheduler()
        s.append({"group": "x", "i": 0})
        s.append({"group": "y", "i": 1})
        assert len(s) == 2
        a = s.popleft()
        s.pushback([a])
        assert s.peek() is a
        assert len(s.drain()) == 2 and len(s) == 0

    def test_composite_queue_names_drive_both_levels(self):
        """The engine API only carries 'queue'; 'paid/alice'-style
        names group by prefix at the outer level (engine.submit(...,
        queue=...) reaches nested fairness without a 'group' key)."""
        s = NestedScheduler(outer=WeightedFairQueue({"paid": 1.0,
                                                     "free": 1.0}))
        for i in range(20):
            s.append({"queue": "paid/alice", "i": i})
            s.append({"queue": "paid/bob", "i": 100 + i})
        for i in range(20):
            s.append({"queue": "free/eve", "i": 200 + i})
        head = [s.popleft()["queue"].split("/")[0] for _ in range(20)]
        # outer fairness across paid vs free despite 2:1 item counts
        assert 7 <= head.count("free") <= 13, head


class TestDrainReappendCycles:

    def test_fairness_survives_batcher_style_cycles(self):
        """The batcher serves via take() (controller.py _run): skipped
        items keep their original tags and only taken items advance
        virtual time.  Across many overloaded cycles the service ratio
        must follow the weights — neither frozen FIFO (a front-deque
        drain/pushback cycle) nor low-weight starvation (re-tagging the
        rest) — both round-5 review catches."""
        s = WeightedFairQueue({"paid": 4.0, "free": 1.0})
        served = {"paid": 0, "free": 0}
        for cycle in range(200):
            # steady arrivals, service of 1/cycle (overloaded)
            s.append(_item("paid", cycle))
            s.append(_item("free", cycle))
            got = []

            def sel(item, got=got):
                if got:
                    return "stop"
                got.append(item)
                return "take"

            s.take(sel)
            served[got[0]["queue"]] += 1
        # overload service ratio follows the weights; free NOT starved
        assert served["free"] >= 25, served
        ratio = served["paid"] / served["free"]
        assert 3.0 <= ratio <= 5.5, served

    def test_take_skips_preserve_priority_and_state(self):
        """Skipped items keep their tags (no re-tagging, no front
        freeze): taking only 'b' items leaves 'a' items in FIFO order
        at their original priority, and a later unrestricted take sees
        them first."""
        s = WeightedFairQueue({"a": 1.0, "b": 1.0})
        for i in range(3):
            s.append(_item("a", i))
        for i in range(3):
            s.append(_item("b", i))
        only_b = s.take(lambda it: "take" if it["queue"] == "b"
                        else "skip")
        assert [it["i"] for it in only_b] == [0, 1, 2]
        assert len(s) == 3
        rest = s.take(lambda it: "take")
        assert [(it["queue"], it["i"]) for it in rest] == \
            [("a", 0), ("a", 1), ("a", 2)]

    def test_take_stop_leaves_remainder_intact(self):
        s = WeightedFairQueue()
        for i in range(5):
            s.append(_item("default", i))
        got = s.take(lambda it: "take" if it["i"] < 2 else "stop")
        assert [it["i"] for it in got] == [0, 1]
        assert len(s) == 3
        assert [it["i"] for it in s.drain()] == [2, 3, 4]

    def test_nested_take_offers_group_heads_in_outer_order(self):
        s = NestedScheduler(outer=WeightedFairQueue({"paid": 1.0,
                                                     "free": 1.0}))
        for i in range(3):
            s.append({"queue": "paid/x", "i": i})
            s.append({"queue": "free/y", "i": 10 + i})
        # take only free heads; paid group skipped wholesale, intact
        got = s.take(lambda it: "take"
                     if it["queue"].startswith("free") else "skip")
        assert [it["i"] for it in got] == [10, 11, 12]
        assert len(s) == 3
        assert [it["i"] for it in s.drain()] == [0, 1, 2]


class TestTagPruning:

    def test_unique_queue_names_do_not_grow_state_unboundedly(self):
        s = WeightedFairQueue()
        for i in range(5000):
            s.append({"queue": f"q{i}", "i": i})
            s.popleft()
        assert len(s._last_tag) <= 1100, len(s._last_tag)


class TestEngineIntegration:

    def test_engine_with_weighted_scheduler_stays_exact(self):
        """Outputs are byte-identical to plain generation regardless of
        admission order; requests carry queue names."""
        import threading

        from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
        from alpa_tpu.serve.engine import ContinuousBatchingEngine
        from alpa_tpu.serve.generation import (GenerationConfig,
                                               Generator)

        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=32, vocab_size=64)
        model, params = init_gpt_real(cfg, 1)
        gen = Generator(model, params, cfg, batch_size=1,
                        prompt_buckets=[8])
        eng = ContinuousBatchingEngine(
            gen, max_batch=2,
            scheduler=WeightedFairQueue({"paid": 4.0, "free": 1.0}))
        try:
            prompts = [np.array([i + 1, i + 2], np.int32)
                       for i in range(6)]
            want = [gen.generate(p[None],
                                 GenerationConfig(max_new_tokens=4))
                    for p in prompts]
            res = [None] * 6

            def go(i):
                res[i] = eng.submit(
                    prompts[i], GenerationConfig(max_new_tokens=4),
                    queue="paid" if i % 2 == 0 else "free")

            ts = [threading.Thread(target=go, args=(i,))
                  for i in range(6)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            for i in range(6):
                np.testing.assert_array_equal(res[i],
                                              np.asarray(want[i])[0])
        finally:
            eng.shutdown()

    def test_batcher_with_weighted_scheduler_stays_exact(self):
        """The batched path forms batches in policy order; outputs stay
        byte-identical to plain generation, and mixed sampling groups
        still split correctly."""
        import threading

        from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
        from alpa_tpu.serve.controller import RequestBatcher
        from alpa_tpu.serve.generation import (GenerationConfig,
                                               Generator)

        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=32, vocab_size=64)
        model, params = init_gpt_real(cfg, 1)
        gen = Generator(model, params, cfg, batch_size=1,
                        prompt_buckets=[8])
        batcher = RequestBatcher(
            gen, max_batch=4,
            scheduler=WeightedFairQueue({"paid": 4.0}))
        prompts = [np.array([i + 1, i + 3], np.int32) for i in range(4)]
        cfgs = [GenerationConfig(max_new_tokens=4),
                GenerationConfig(max_new_tokens=4),
                GenerationConfig(max_new_tokens=4, eos_token_id=63),
                GenerationConfig(max_new_tokens=4)]
        want = [gen.generate(p[None], c)
                for p, c in zip(prompts, cfgs)]
        res = [None] * 4

        def go(i):
            res[i] = batcher.submit(
                [prompts[i]], cfgs[i],
                queue="paid" if i % 2 == 0 else "free")

        ts = [threading.Thread(target=go, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for i in range(4):
            row = np.asarray(want[i])[0]
            if cfgs[i].eos_token_id is not None:
                hits = np.nonzero(row[2:] == cfgs[i].eos_token_id)[0]
                if hits.size:
                    row = row[:2 + hits[0] + 1]
            np.testing.assert_array_equal(res[i][0], row)

    def test_fifo_queue_protocol(self):
        s = FIFOQueue()
        for i in range(3):
            s.append(i)
        assert s.peek() == 0
        a = s.popleft()
        s.pushback([a])
        assert [s.popleft() for _ in range(3)] == [0, 1, 2]
        s.append(9)
        assert s.drain() == [9] and len(s) == 0


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestTakeExceptionSafety:
    """A faulty selector must never strand items outside the queue:
    taken-so-far items return to the front, in-flight and unvisited
    items stay, and the error propagates."""

    @pytest.mark.parametrize("factory", [
        FIFOQueue,
        lambda: WeightedFairQueue({"a": 2.0}),
        lambda: NestedScheduler(outer=WeightedFairQueue()),
    ])
    def test_no_item_lost_on_selector_raise(self, factory):
        s = factory()
        items = [{"queue": "a/x", "i": i} for i in range(6)]
        for it in items:
            s.append(it)

        calls = [0]

        def bad(item):
            calls[0] += 1
            if calls[0] == 3:
                raise RuntimeError("boom")
            return "take"

        with pytest.raises(RuntimeError, match="boom"):
            s.take(bad)
        assert len(s) == 6
        assert sorted(it["i"] for it in s.drain()) == list(range(6))


class TestProtocolFuzz:
    """Conservation fuzz: under random interleavings of every protocol
    operation, no item is ever lost or duplicated, len() stays
    consistent, and drain() always empties."""

    @pytest.mark.parametrize("factory,seed", [
        (FIFOQueue, 0),
        (lambda: WeightedFairQueue({"a": 3.0, "b": 1.0}), 1),
        (lambda: NestedScheduler(outer=WeightedFairQueue({"a": 2.0})), 2),
    ])
    def test_conservation_under_random_ops(self, factory, seed):
        rng = np.random.RandomState(seed)
        s = factory()
        inside = {}          # id -> item currently owned by the queue
        outside = []         # items popped/taken, eligible for pushback
        next_id = [0]

        def new_item():
            q = ["a/x", "a/y", "b/z"][rng.randint(3)]
            item = {"queue": q, "id": next_id[0]}
            next_id[0] += 1
            return item

        for _ in range(3000):
            op = rng.randint(6)
            if op <= 1:                                   # append
                it = new_item()
                s.append(it)
                inside[it["id"]] = it
            elif op == 2 and len(s):                      # popleft
                it = s.popleft()
                del inside[it["id"]]
                outside.append(it)
            elif op == 3 and len(s):                      # take(random)
                def sel(item, r=rng):
                    return ("take", "skip", "stop")[r.randint(3)]
                got = s.take(sel)
                for it in got:
                    del inside[it["id"]]
                    outside.append(it)
            elif op == 4 and outside:                     # pushback some
                k = rng.randint(1, min(4, len(outside)) + 1)
                back, outside[:] = outside[:k], outside[k:]
                s.pushback(back)
                for it in back:
                    inside[it["id"]] = it
            elif op == 5 and rng.random() < 0.05:         # rare drain
                for it in s.drain():
                    del inside[it["id"]]
                    outside.append(it)
            assert len(s) == len(inside), (len(s), len(inside))

        drained = s.drain()
        assert sorted(it["id"] for it in drained) == sorted(inside)
        assert len(s) == 0
