"""Flash / ring attention vs the einsum reference."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from alpa_tpu.model.gpt_model import reference_attention
from alpa_tpu.ops.flash_attention import flash_attention
from alpa_tpu.ops.ring_attention import make_ring_attention_fn, ring_attention


def _rand_qkv(b=2, s=128, h=4, d=32, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    return tuple(
        jax.random.normal(k, (b, s, h, d), dtype) * 0.5 for k in ks)


class TestFlashAttention:

    @pytest.mark.parametrize("causal", [True, False])
    def test_forward_matches_reference(self, causal):
        q, k, v = _rand_qkv()
        out = flash_attention(q, k, v, causal=causal)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_backward_matches_reference(self):
        q, k, v = _rand_qkv(s=64)

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, causal=True)**2).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, causal=True)**2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-4)

    def test_uneven_blocks(self):
        q, k, v = _rand_qkv(s=96)  # not a multiple of default block sizes
        out = flash_attention(q, k, v, causal=True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("causal", [True, False])
    def test_kernel_backward_matches_reference(self, causal):
        """The VMEM-resident regime uses the real pallas backward kernels
        (dq; dk/dv off saved out+logsumexp) — gradients must match the
        reference, including across block boundaries (s > block sizes)."""
        from alpa_tpu.ops.flash_attention import VMEM_RESIDENT_LIMIT
        q, k, v = _rand_qkv(s=512, d=64)
        itemsize = jnp.dtype(q.dtype).itemsize
        assert 2 * 512 * 64 * itemsize <= VMEM_RESIDENT_LIMIT

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, causal=causal)**2).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, causal=causal)**2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)

    def test_streaming_backward_falls_back(self):
        """Beyond the VMEM budget the backward takes the chunked
        recompute path and still matches the reference."""
        from alpa_tpu.ops.flash_attention import VMEM_RESIDENT_LIMIT
        q, k, v = _rand_qkv(b=1, s=16384, h=1, d=64)
        assert 2 * 16384 * 64 * 4 > VMEM_RESIDENT_LIMIT

        def loss_flash(q, k, v):
            return (flash_attention(q, k, v, causal=True)**2).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, causal=True)**2).sum()

        gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gf, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=3e-4)


class TestRingAttention:

    def _mesh(self, n=4):
        devs = np.array(jax.devices()[:n])
        return Mesh(devs, ("sp",))

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference(self, causal):
        mesh = self._mesh()
        q, k, v = _rand_qkv(s=64)
        attn = make_ring_attention_fn(mesh, "sp")
        with jax.set_mesh(mesh):
            out = jax.jit(lambda q, k, v: attn(q, k, v, causal=causal))(
                q, k, v)
        ref = reference_attention(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)

    def test_gradients_flow(self):
        mesh = self._mesh()
        q, k, v = _rand_qkv(s=64)
        attn = make_ring_attention_fn(mesh, "sp")

        def loss(q, k, v):
            return (attn(q, k, v, causal=True)**2).sum()

        def loss_ref(q, k, v):
            return (reference_attention(q, k, v, causal=True)**2).sum()

        with jax.set_mesh(mesh):
            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestUlyssesAttention:

    def _mesh(self, n=4):
        return Mesh(np.array(jax.devices()[:n]), ("sp",))

    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_reference_exactly(self, causal):
        from alpa_tpu.ops.ulysses_attention import make_ulysses_attention_fn
        mesh = self._mesh()
        q, k, v = _rand_qkv(s=64, h=8)
        attn = make_ulysses_attention_fn(mesh, "sp")
        with jax.set_mesh(mesh):
            out = jax.jit(lambda q, k, v: attn(q, k, v, causal=causal))(
                q, k, v)
        ref = reference_attention(q, k, v, causal=causal)
        # all-to-all only moves data; differences are float reduction order
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_gradients(self):
        from alpa_tpu.ops.ulysses_attention import make_ulysses_attention_fn
        mesh = self._mesh()
        q, k, v = _rand_qkv(s=64, h=8)
        attn = make_ulysses_attention_fn(mesh, "sp")

        def loss(q, k, v):
            return (attn(q, k, v, causal=True)**2).sum()

        with jax.set_mesh(mesh):
            g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(
            lambda q, k, v:
            (reference_attention(q, k, v, causal=True)**2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-5)

    def test_composes_with_flash_kernel(self):
        """Ulysses SP + the pallas flash kernel per head shard: the
        all-to-all hands each device the FULL sequence for its heads, so
        the blocked kernel applies unchanged — fwd and grads match the
        reference."""
        from alpa_tpu.ops.flash_attention import flash_attention
        from alpa_tpu.ops.ulysses_attention import make_ulysses_attention_fn
        mesh = self._mesh()
        q, k, v = _rand_qkv(s=256, h=8, d=32)
        attn = make_ulysses_attention_fn(mesh, "sp",
                                         attn_fn=flash_attention)
        with jax.set_mesh(mesh):
            out = jax.jit(lambda q, k, v: attn(q, k, v, causal=True))(
                q, k, v)
            g = jax.jit(jax.grad(
                lambda q, k, v: (attn(q, k, v, causal=True)**2).sum(),
                argnums=(0, 1, 2)))(q, k, v)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
        gr = jax.grad(
            lambda q, k, v:
            (reference_attention(q, k, v, causal=True)**2).sum(),
            argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-4, atol=3e-4)

    def test_indivisible_heads_clear_error(self):
        from alpa_tpu.ops.ulysses_attention import make_ulysses_attention_fn
        mesh = self._mesh()
        q, k, v = _rand_qkv(s=64, h=6)  # 6 heads, 4-way axis
        attn = make_ulysses_attention_fn(mesh, "sp")
        with pytest.raises(Exception, match="divisible|not divisible"):
            with jax.set_mesh(mesh):
                jax.jit(lambda q, k, v: attn(q, k, v))(q, k, v)


class TestStreamingFlash:

    def test_long_sequence_streaming_path(self):
        """k/v beyond the VMEM-resident limit take the HBM-streaming
        kernel; result must match the reference exactly."""
        from alpa_tpu.ops.flash_attention import (VMEM_RESIDENT_LIMIT,
                                                  flash_attention)
        s, d = 16384, 64
        assert 2 * s * d * 4 > VMEM_RESIDENT_LIMIT  # streaming triggers
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (1, s, 1, d)) * 0.5 for kk in ks)
        out = flash_attention(q, k, v, causal=True)
        ref = reference_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
