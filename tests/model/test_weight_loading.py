"""HF GPT-2 weight conversion parity (ref llm_serving weight loading)."""
import json
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from alpa_tpu.model.weight_loading import load_gpt2


class TestGPT2Loading:

    def test_logits_match_transformers(self):
        from transformers import GPT2Config, GPT2LMHeadModel

        hf_config = GPT2Config(vocab_size=128, n_positions=32, n_embd=48,
                               n_layer=2, n_head=4,
                               attn_pdrop=0.0, resid_pdrop=0.0,
                               embd_pdrop=0.0)
        hf_model = GPT2LMHeadModel(hf_config).eval()
        model, params, config = load_gpt2(hf_model)

        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        with torch.no_grad():
            want = hf_model(torch.tensor(ids)).logits.numpy()
        got = np.asarray(model.apply(params, jnp.asarray(ids)))
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)

    def test_opt_logits_match_transformers(self):
        """OPT family (the reference's flagship serving model, ref
        examples/llm_serving/model/opt_model.py)."""
        from transformers import OPTConfig, OPTForCausalLM

        from alpa_tpu.model.weight_loading import load_opt

        hf_config = OPTConfig(vocab_size=128, hidden_size=48,
                              num_hidden_layers=2, num_attention_heads=4,
                              ffn_dim=192, max_position_embeddings=32,
                              do_layer_norm_before=True,
                              activation_function="relu", dropout=0.0,
                              attention_dropout=0.0)
        hf_model = OPTForCausalLM(hf_config).eval()
        model, params, config = load_opt(hf_model)
        assert config.activation == "relu" and config.pos_offset == 2

        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        with torch.no_grad():
            want = hf_model(torch.tensor(ids)).logits.numpy()
        got = np.asarray(model.apply(params, jnp.asarray(ids)))
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)

    def test_opt_generate_matches_transformers(self):
        from transformers import OPTConfig, OPTForCausalLM

        from alpa_tpu.model.weight_loading import load_opt
        from alpa_tpu.serve import Generator

        hf_config = OPTConfig(vocab_size=128, hidden_size=48,
                              num_hidden_layers=2, num_attention_heads=4,
                              ffn_dim=192, max_position_embeddings=32,
                              do_layer_norm_before=True,
                              activation_function="relu", dropout=0.0,
                              attention_dropout=0.0)
        hf_model = OPTForCausalLM(hf_config).eval()
        model, params, config = load_opt(hf_model)
        from alpa_tpu.serve import GenerationConfig
        gen = Generator(model, params, config)
        ids = np.random.RandomState(1).randint(4, 128, (1, 8))
        out = gen.generate(ids, GenerationConfig(max_new_tokens=16))
        want = hf_model.generate(torch.tensor(ids), max_new_tokens=16,
                                 do_sample=False).numpy()
        np.testing.assert_array_equal(np.asarray(out)[:, :want.shape[1]],
                                      want)

    def test_sharded_loading(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from transformers import GPT2Config, GPT2LMHeadModel

        hf_config = GPT2Config(vocab_size=128, n_positions=32, n_embd=64,
                               n_layer=1, n_head=4)
        hf_model = GPT2LMHeadModel(hf_config)
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("tp",))
        model, params0, config = load_gpt2(hf_model)
        shardings = jax.tree_util.tree_map(
            lambda x: NamedSharding(mesh, P("tp", None))
            if np.ndim(x) == 2 and x.shape[0] % 8 == 0 else
            NamedSharding(mesh, P()), params0)
        model, params, config = load_gpt2(hf_model, shardings=shardings)
        leaf = params["params"]["wte"]["embedding"]
        assert leaf.sharding.is_equivalent_to(
            NamedSharding(mesh, P("tp", None)), 2)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestDiskShardedLoading:
    """175B-class loading path (ref load_params_dis_array,
    opt_model.py:956): per-parameter files -> sharded arrays, reading
    only each shard's slices via memmap."""

    def test_roundtrip_sharded(self, tmp_path):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from alpa_tpu.model.weight_loading import (load_params_dir,
                                                   save_params_dir)

        params = {
            "wte": {"embedding":
                    np.random.RandomState(0).randn(64, 16).astype(
                        np.float32)},
            "h0": {"mlp": {"kernel":
                           np.random.RandomState(1).randn(16, 32).astype(
                               np.float32)}},
        }
        d = str(tmp_path / "ckpt")
        save_params_dir(params, d)

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("tp",))
        shardings = {
            "wte": {"embedding": NamedSharding(mesh, P("tp", None))},
            "h0": {"mlp": {"kernel": NamedSharding(mesh, P(None, "tp"))}},
        }
        loaded = load_params_dir(d, shardings)
        np.testing.assert_allclose(np.asarray(loaded["wte"]["embedding"]),
                                   params["wte"]["embedding"])
        np.testing.assert_allclose(
            np.asarray(loaded["h0"]["mlp"]["kernel"]),
            params["h0"]["mlp"]["kernel"])
        # landed sharded, not replicated
        assert len(loaded["wte"]["embedding"].sharding.device_set) == 8
        shard0 = loaded["wte"]["embedding"].addressable_shards[0]
        assert shard0.data.shape == (8, 16)

    def test_replicated_leaf_and_model_apply(self, tmp_path):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from alpa_tpu.model.gpt_model import GPTConfig, GPTModel, \
            init_gpt_real
        from alpa_tpu.model.weight_loading import (load_params_dir,
                                                   save_params_dir)

        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=4, seq_len=16)
        model, params = init_gpt_real(cfg, 1)
        d = str(tmp_path / "gpt")
        save_params_dir(params, d)
        # None = replicate each leaf
        shardings = jax.tree_util.tree_map(lambda _: None, params)
        loaded = load_params_dir(d, shardings)
        ids = np.random.RandomState(0).randint(0, 64, (1, 8))
        want = model.apply(params, jnp.asarray(ids))
        got = model.apply(loaded, jnp.asarray(ids))
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-6, atol=1e-6)


class TestLoadingDrill:
    """The 10B-class loading drill's wiring at toy scale (VERDICT r4
    next #10): synthesize-to-disk -> tp-sharded memmap load -> jit
    forward, AND the same checkpoint through a pipeshard inference
    executable, both verified against an independent streamed
    layer-at-a-time reference.  scripts/loading_drill_10b.py runs the
    identical code at ~10B params; its artifact is
    benchmark/results/loading_drill_10b.json."""

    def test_drill_small_mode(self):
        import subprocess
        import sys

        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        proc = subprocess.run(
            [sys.executable,
             os.path.join(repo, "scripts", "loading_drill_10b.py"),
             "--small", "--dir", "/tmp/loading_drill_test"],
            capture_output=True, text=True, timeout=600)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        last = json.loads(proc.stdout.strip().splitlines()[-1])
        assert last["tp8_rel_diff"] < 1e-3
        assert last["pipeshard_rel_diff"] < 1e-3
