"""HF GPT-2 weight conversion parity (ref llm_serving weight loading)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from alpa_tpu.model.weight_loading import load_gpt2


class TestGPT2Loading:

    def test_logits_match_transformers(self):
        from transformers import GPT2Config, GPT2LMHeadModel

        hf_config = GPT2Config(vocab_size=128, n_positions=32, n_embd=48,
                               n_layer=2, n_head=4,
                               attn_pdrop=0.0, resid_pdrop=0.0,
                               embd_pdrop=0.0)
        hf_model = GPT2LMHeadModel(hf_config).eval()
        model, params, config = load_gpt2(hf_model)

        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        with torch.no_grad():
            want = hf_model(torch.tensor(ids)).logits.numpy()
        got = np.asarray(model.apply(params, jnp.asarray(ids)))
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)

    def test_opt_logits_match_transformers(self):
        """OPT family (the reference's flagship serving model, ref
        examples/llm_serving/model/opt_model.py)."""
        from transformers import OPTConfig, OPTForCausalLM

        from alpa_tpu.model.weight_loading import load_opt

        hf_config = OPTConfig(vocab_size=128, hidden_size=48,
                              num_hidden_layers=2, num_attention_heads=4,
                              ffn_dim=192, max_position_embeddings=32,
                              do_layer_norm_before=True,
                              activation_function="relu", dropout=0.0,
                              attention_dropout=0.0)
        hf_model = OPTForCausalLM(hf_config).eval()
        model, params, config = load_opt(hf_model)
        assert config.activation == "relu" and config.pos_offset == 2

        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        with torch.no_grad():
            want = hf_model(torch.tensor(ids)).logits.numpy()
        got = np.asarray(model.apply(params, jnp.asarray(ids)))
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)

    def test_opt_generate_matches_transformers(self):
        from transformers import OPTConfig, OPTForCausalLM

        from alpa_tpu.model.weight_loading import load_opt
        from alpa_tpu.serve import Generator

        hf_config = OPTConfig(vocab_size=128, hidden_size=48,
                              num_hidden_layers=2, num_attention_heads=4,
                              ffn_dim=192, max_position_embeddings=32,
                              do_layer_norm_before=True,
                              activation_function="relu", dropout=0.0,
                              attention_dropout=0.0)
        hf_model = OPTForCausalLM(hf_config).eval()
        model, params, config = load_opt(hf_model)
        from alpa_tpu.serve import GenerationConfig
        gen = Generator(model, params, config)
        ids = np.random.RandomState(1).randint(4, 128, (1, 8))
        out = gen.generate(ids, GenerationConfig(max_new_tokens=16))
        want = hf_model.generate(torch.tensor(ids), max_new_tokens=16,
                                 do_sample=False).numpy()
        np.testing.assert_array_equal(np.asarray(out)[:, :want.shape[1]],
                                      want)

    def test_sharded_loading(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from transformers import GPT2Config, GPT2LMHeadModel

        hf_config = GPT2Config(vocab_size=128, n_positions=32, n_embd=64,
                               n_layer=1, n_head=4)
        hf_model = GPT2LMHeadModel(hf_config)
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("tp",))
        model, params0, config = load_gpt2(hf_model)
        shardings = jax.tree_util.tree_map(
            lambda x: NamedSharding(mesh, P("tp", None))
            if np.ndim(x) == 2 and x.shape[0] % 8 == 0 else
            NamedSharding(mesh, P()), params0)
        model, params, config = load_gpt2(hf_model, shardings=shardings)
        leaf = params["params"]["wte"]["embedding"]
        assert leaf.sharding.is_equivalent_to(
            NamedSharding(mesh, P("tp", None)), 2)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
