"""HF GPT-2 weight conversion parity (ref llm_serving weight loading)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from alpa_tpu.model.weight_loading import load_gpt2


class TestGPT2Loading:

    def test_logits_match_transformers(self):
        from transformers import GPT2Config, GPT2LMHeadModel

        hf_config = GPT2Config(vocab_size=128, n_positions=32, n_embd=48,
                               n_layer=2, n_head=4,
                               attn_pdrop=0.0, resid_pdrop=0.0,
                               embd_pdrop=0.0)
        hf_model = GPT2LMHeadModel(hf_config).eval()
        model, params, config = load_gpt2(hf_model)

        ids = np.random.RandomState(0).randint(0, 128, (2, 16))
        with torch.no_grad():
            want = hf_model(torch.tensor(ids)).logits.numpy()
        got = np.asarray(model.apply(params, jnp.asarray(ids)))
        np.testing.assert_allclose(got, want, rtol=5e-3, atol=5e-3)

    def test_sharded_loading(self):
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from transformers import GPT2Config, GPT2LMHeadModel

        hf_config = GPT2Config(vocab_size=128, n_positions=32, n_embd=64,
                               n_layer=1, n_head=4)
        hf_model = GPT2LMHeadModel(hf_config)
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("tp",))
        model, params0, config = load_gpt2(hf_model)
        shardings = jax.tree_util.tree_map(
            lambda x: NamedSharding(mesh, P("tp", None))
            if np.ndim(x) == 2 and x.shape[0] % 8 == 0 else
            NamedSharding(mesh, P()), params0)
        model, params, config = load_gpt2(hf_model, shardings=shardings)
        leaf = params["params"]["wte"]["embedding"]
        assert leaf.sharding.is_equivalent_to(
            NamedSharding(mesh, P("tp", None)), 2)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
