"""Model zoo smoke + parallelization tests."""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training import train_state

import alpa_tpu
from alpa_tpu import ShardParallel
from alpa_tpu.model.bert_model import BertConfig, BertForMaskedLM
from alpa_tpu.model.gpt_model import GPTConfig, GPTModel, init_kv_caches
from alpa_tpu.model.moe import MoEConfig, MoELMModel
from alpa_tpu.model.model_util import cross_entropy_loss
from alpa_tpu.model.wide_resnet import WResNetConfig, WideResNet
from alpa_tpu.testing import assert_allclose


class TestGPT:

    def test_forward_and_cache_decode(self):
        """Incremental decoding with KV cache == full forward."""
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=16, vocab_size=64)
        model = GPTModel(cfg)
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (2, 16), 0, 64)
        params = model.init(rng, ids)
        full_logits = model.apply(params, ids)

        caches = init_kv_caches(cfg, batch_size=2)
        for t in range(16):
            step_ids = ids[:, t:t + 1]
            pos = jnp.full((2, 1), t, jnp.int32)
            logits, caches = model.apply(params, step_ids, pos, caches)
        np.testing.assert_allclose(np.asarray(logits[:, 0]),
                                   np.asarray(full_logits[:, -1]),
                                   rtol=2e-4, atol=2e-4)


class TestMoE:

    @pytest.mark.slow
    def test_moe_trains_with_expert_parallel(self):
        cfg = MoEConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, seq_len=16, num_experts=4,
                        expert_group_size=32, moe_every=2, ep_axis=None)
        model = MoELMModel(cfg)
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (8, 16), 0, 64)
        labels = jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0, 64)
        params = model.init(rng, ids)
        state = train_state.TrainState.create(apply_fn=model.apply,
                                              params=params,
                                              tx=optax.adam(1e-3))

        @alpa_tpu.parallelize(method=ShardParallel())
        def step(state, batch):

            def loss_fn(p):
                logits, aux = state.apply_fn(p, batch["ids"])
                return cross_entropy_loss(
                    logits.astype(jnp.float32),
                    batch["labels"]) + 0.01 * aux

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), loss

        batch = {"ids": ids, "labels": labels}
        losses = []
        for _ in range(5):
            state, loss = step(state, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0], losses

    def test_gating_respects_capacity(self):
        from alpa_tpu.model.moe import top2_gating
        logits = jax.random.normal(jax.random.PRNGKey(0), (2, 32, 4))
        combine, dispatch, aux = top2_gating(logits, capacity=8)
        assert combine.shape == (2, 32, 4, 8)
        # each expert slot used by at most one token
        per_slot = dispatch.sum(axis=1)  # (G, E, C)
        assert float(per_slot.max()) <= 1.0 + 1e-6
        assert np.isfinite(float(aux))

    def test_moe_decode_matches_full_context(self):
        """Mixtral-style MoE decoding: KV-cached incremental decode
        equals the full-context forward.  capacity_factor >= num_experts
        guarantees no capacity drops, which would otherwise make routing
        depend on how many tokens share the pass."""
        from alpa_tpu.model.moe import init_moe_kv_caches
        cfg = MoEConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, seq_len=16, num_experts=4,
                        capacity_factor=4.0, expert_group_size=32,
                        moe_every=2, ep_axis=None)
        model = MoELMModel(cfg)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 64, (2, 10)).astype(np.int32)
        params = model.init(jax.random.PRNGKey(0), jnp.asarray(ids))
        full, _aux = model.apply(params, jnp.asarray(ids))
        full = np.asarray(full)

        caches = init_moe_kv_caches(cfg, 2)
        logits_p, caches = model.apply(params, jnp.asarray(ids[:, :6]),
                                       None, caches)
        np.testing.assert_allclose(np.asarray(logits_p), full[:, :6],
                                   rtol=5e-4, atol=5e-4)
        for t in range(6, 10):
            # learned position table: absolute positions must be passed
            # for incremental decode (the Generator does this)
            pos = jnp.full((2, 1), t, jnp.int32)
            step, caches = model.apply(params, jnp.asarray(ids[:, t:t + 1]),
                                       pos, caches)
            np.testing.assert_allclose(np.asarray(step)[:, 0], full[:, t],
                                       rtol=5e-4, atol=5e-4)

    def test_moe_serves_through_generator(self):
        """The serving Generator drives the MoE LM unchanged (cache-as-
        invars contract parity)."""
        from alpa_tpu.serve.generation import GenerationConfig, Generator
        cfg = MoEConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, seq_len=32, num_experts=4,
                        capacity_factor=4.0, expert_group_size=64,
                        moe_every=2, ep_axis=None)
        model = MoELMModel(cfg)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.ones((1, 8), jnp.int32))
        gen = Generator(model, params, cfg, batch_size=1,
                        prompt_buckets=[8])
        out = gen.generate(np.array([[1, 2, 3]], np.int32),
                           GenerationConfig(max_new_tokens=5))
        assert out.shape == (1, 8)
        # greedy replay without cache
        replay = np.array([[1, 2, 3]], np.int32)
        for _ in range(5):
            lg, _aux = model.apply(params, jnp.asarray(replay))
            nxt = np.argmax(np.asarray(lg[:, -1]), -1)
            replay = np.concatenate([replay, nxt[:, None].astype(np.int32)],
                                    axis=1)
        np.testing.assert_array_equal(out, replay)


class TestBert:

    def test_mlm_forward_and_train(self):
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                         num_heads=4, seq_len=16)
        model = BertForMaskedLM(cfg)
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (4, 16), 0, 64)
        params = model.init(rng, ids)
        logits = model.apply(params, ids)
        assert logits.shape == (4, 16, 64)
        # bidirectional: perturbing a late token changes early logits
        ids2 = ids.at[:, -1].set((ids[:, -1] + 1) % 64)
        logits2 = model.apply(params, ids2)
        assert not np.allclose(np.asarray(logits[:, 0]),
                               np.asarray(logits2[:, 0]))

    def test_attention_mask_blocks_padding(self):
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                         num_heads=4, seq_len=16)
        model = BertForMaskedLM(cfg)
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (2, 16), 0, 64)
        mask = jnp.concatenate([jnp.ones((2, 12), jnp.int32),
                                jnp.zeros((2, 4), jnp.int32)], axis=1)
        params = model.init(rng, ids, mask)
        base = model.apply(params, ids, mask)
        # changing tokens under the padding mask must not change valid
        # positions' logits
        ids2 = ids.at[:, -1].set((ids[:, -1] + 7) % 64)
        out2 = model.apply(params, ids2, mask)
        np.testing.assert_allclose(np.asarray(base[:, :12]),
                                   np.asarray(out2[:, :12]),
                                   rtol=1e-6, atol=1e-6)
        # without the mask they do change (sanity)
        out3 = model.apply(params, ids2)
        assert not np.allclose(np.asarray(base[:, :12]),
                               np.asarray(out3[:, :12]))

    def test_pretraining_heads_and_loss(self):
        from alpa_tpu.model.bert_model import (BertForPreTraining,
                                               bert_pretraining_loss)
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=2,
                         num_heads=4, seq_len=16, tie_word_embeddings=True)
        model = BertForPreTraining(cfg)
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (4, 16), 0, 64)
        params = model.init(rng, ids)
        # tied decoder: no separate (H, V) decoder kernel in the tree
        flat = jax.tree_util.tree_leaves_with_path(params)
        assert not any("decoder/" in jax.tree_util.keystr(p).replace(
            "']['", "/") and l.ndim == 2 for p, l in flat)
        mlm_logits, nsp_logits = model.apply(params, ids)
        assert mlm_logits.shape == (4, 16, 64)
        assert nsp_logits.shape == (4, 2)

        mlm_labels = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0,
                                        64)
        mlm_weights = (jax.random.uniform(jax.random.PRNGKey(2),
                                          (4, 16)) < 0.15).astype(
                                              jnp.float32)
        nsp_labels = jnp.array([0, 1, 0, 1])

        def loss_fn(p):
            ml, nl = model.apply(p, ids)
            return bert_pretraining_loss(ml, nl, mlm_labels, mlm_weights,
                                         nsp_labels)

        loss, grads = jax.value_and_grad(loss_fn)(params)
        assert np.isfinite(float(loss))
        # the tied embedding table receives gradient from the MLM head
        g_emb = grads["params"]["bert"]["word_embeddings"]["embedding"]
        assert float(jnp.abs(g_emb).max()) > 0


class TestWideResNet:

    def test_forward_and_parallel_train(self):
        cfg = WResNetConfig(num_layers=50, width_factor=1, num_classes=10)
        model = WideResNet(cfg)
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (8, 32, 32, 3))
        y = jax.random.randint(jax.random.PRNGKey(1), (8,), 0, 10)
        params = model.init(rng, x)
        state = train_state.TrainState.create(apply_fn=model.apply,
                                              params=params,
                                              tx=optax.sgd(1e-2))

        @alpa_tpu.parallelize(method=alpa_tpu.DataParallel())
        def step(state, batch):

            def loss_fn(p):
                logits = state.apply_fn(p, batch["x"])
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, batch["y"]).mean()

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), loss

        state, loss = step(state, {"x": x, "y": y})
        assert np.isfinite(float(loss))


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestUNetAndConformer:

    def test_unet_forward_and_grad(self):
        from alpa_tpu.model.unet_2d import UNet2D, UNetConfig
        cfg = UNetConfig(block_channels=(16, 32), layers_per_block=1,
                         attention_resolutions=(1,), num_heads=2,
                         time_embed_dim=32)
        model = UNet2D(cfg)
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (2, 16, 16, 3))
        t = jnp.array([1, 5])
        params = model.init(rng, x, t)
        out = model.apply(params, x, t)
        assert out.shape == (2, 16, 16, 3)
        g = jax.grad(lambda p: (model.apply(p, x, t)**2).mean())(params)
        assert np.isfinite(float(
            jax.tree_util.tree_leaves(g)[0].sum()))

    def test_unet_condition_model(self):
        from alpa_tpu.model.unet_2d import (UNet2DConditionModel,
                                            UNetConditionConfig)
        cfg = UNetConditionConfig(in_channels=4, out_channels=4,
                                  block_out_channels=(16, 32),
                                  down_block_types=("CrossAttnDownBlock2D",
                                                    "DownBlock2D"),
                                  layers_per_block=1, attention_head_dim=8,
                                  cross_attention_dim=24)
        model = UNet2DConditionModel(cfg)
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (2, 16, 16, 4))
        t = jnp.array([3, 11])
        ctx = jax.random.normal(jax.random.PRNGKey(1), (2, 6, 24))
        params = model.init(rng, x, t, ctx)
        out = model.apply(params, x, t, ctx)
        assert out.shape == (2, 16, 16, 4)
        # conditioning actually conditions: different context, different out
        out2 = model.apply(params, x, t, ctx + 1.0)
        assert not np.allclose(np.asarray(out), np.asarray(out2))
        g = jax.grad(lambda p: (model.apply(p, x, t, ctx)**2).mean())(
            params)
        assert np.isfinite(float(jax.tree_util.tree_leaves(g)[0].sum()))

    def test_unet_auto_sharding_nontrivial(self):
        """The intra-op planner picks a non-trivial (parallel) strategy
        for the UNet's convs on an 8-device mesh (VERDICT r1 next#9)."""
        from alpa_tpu.model.unet_2d import UNet2D, UNetConfig
        from alpa_tpu.util import count_communication_primitives
        cfg = UNetConfig(block_channels=(16, 32), layers_per_block=1,
                         attention_resolutions=(), num_heads=2,
                         time_embed_dim=32)
        model = UNet2D(cfg)
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (16, 16, 16, 3))
        t = jnp.arange(16)
        params = model.init(rng, x, t)
        state = train_state.TrainState.create(apply_fn=model.apply,
                                              params=params,
                                              tx=optax.sgd(1e-2))

        @alpa_tpu.parallelize(method=ShardParallel())
        def step(state, batch):

            def loss_fn(p):
                out = state.apply_fn(p, batch["x"], batch["t"])
                return (out**2).mean()

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), loss

        s, l = step(state, {"x": x, "t": t})
        assert np.isfinite(float(l))
        hlo = step.get_last_executable().get_hlo_text()
        total, ar, ag, rs, a2a = count_communication_primitives(hlo)
        assert total > 0, "UNet compiled with no parallelism at all"

    def test_conformer_asr_with_lengths(self):
        from alpa_tpu.model.conformer import (ConformerConfig,
                                              ConformerForASR)
        cfg = ConformerConfig(num_mel_bins=20, hidden_size=64,
                              num_layers=2, num_heads=4,
                              conv_kernel_size=7, vocab_size=30)
        model = ConformerForASR(cfg)
        rng = jax.random.PRNGKey(0)
        feats = jax.random.normal(rng, (4, 64, 20))
        lengths = jnp.array([64, 48, 32, 16])
        params = model.init(rng, feats, lengths)
        log_probs, out_lens = model.apply(params, feats, lengths)
        assert log_probs.shape == (4, 16, 30)     # T subsampled 4x
        assert list(np.asarray(out_lens)) == [16, 12, 8, 4]
        # log-probs normalized
        np.testing.assert_allclose(
            np.asarray(jnp.exp(log_probs).sum(-1)), 1.0, rtol=1e-3)
        # padding invariance: corrupting frames past a row's length must
        # not change its valid outputs
        feats2 = feats.at[1, 48:].set(99.0)
        lp2, _ = model.apply(params, feats2, lengths)
        np.testing.assert_allclose(np.asarray(log_probs[1, :12]),
                                   np.asarray(lp2[1, :12]), rtol=1e-4,
                                   atol=1e-4)
        # pad-WIDTH invariance: the same audio padded to a different batch
        # width must give the same valid log-probs (no norm reading stats
        # off the time axis)
        solo = jnp.zeros((1, 32, 20)).at[0, :].set(feats[2, :32])
        lp_solo, _ = model.apply(params, solo, jnp.array([32]))
        np.testing.assert_allclose(np.asarray(log_probs[2, :8]),
                                   np.asarray(lp_solo[0, :8]), rtol=1e-4,
                                   atol=1e-4)

    def test_conformer_forward_parallel(self):
        from alpa_tpu.model.conformer import Conformer, ConformerConfig
        cfg = ConformerConfig(hidden_size=64, num_layers=2, num_heads=4,
                              conv_kernel_size=7)
        model = Conformer(cfg)
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (8, 32, 20))
        params = model.init(rng, x)
        out = model.apply(params, x)
        assert out.shape == (8, 32, 64)
        state = train_state.TrainState.create(apply_fn=model.apply,
                                              params=params,
                                              tx=optax.adam(1e-3))

        @alpa_tpu.parallelize(method=ShardParallel())
        def step(state, batch):

            def loss_fn(p):
                y = state.apply_fn(p, batch["x"])
                return (y**2).mean()

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), loss

        s, l = step(state, {"x": x})
        assert np.isfinite(float(l))


class TestExpertParallelStructure:

    def test_ep_sharding_uses_all_to_all_dispatch(self):
        """Expert parallelism dispatches tokens with the GShard all-to-all
        pattern (explicit shard_map exchange), NOT all-gathers, and
        matches the dense-dispatch numerics for the same grouping."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from alpa_tpu.model.moe import MoEConfig, MoEMLP
        from alpa_tpu.util import count_communication_primitives

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("ep",))
        # expert_group_size 32 -> 8 groups either way (divisible by ep=8)
        kw = dict(vocab_size=64, hidden_size=64, num_layers=1,
                  num_heads=4, seq_len=32, num_experts=8,
                  expert_group_size=32, moe_every=1)
        m = MoEMLP(MoEConfig(ep_axis="ep", **kw))
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (8, 32, 64))
        with jax.set_mesh(mesh):
            params = m.init(rng, x)
            f = jax.jit(lambda p, xx: m.apply(p, xx)[0],
                        in_shardings=(None, NamedSharding(mesh, P("ep"))))
            hlo = f.lower(params, x).compile().as_text()
            out_sharded = f(params, x)
        total, ar, ag, rs, a2a = count_communication_primitives(hlo)
        assert a2a >= 2, (total, ar, ag, rs, a2a)
        assert ag == 0, f"dispatch fell back to all-gathers: {ag}"
        out_ref = MoEMLP(MoEConfig(ep_axis=None, **kw)).apply(params, x)[0]
        np.testing.assert_allclose(np.asarray(out_sharded),
                                   np.asarray(out_ref), rtol=2e-5,
                                   atol=2e-5)


class TestDynamicScale:
    """Mixed-precision loss scaling (ref model_util.py TrainState +
    dynamic scale): scale backs off on overflow, grows after a streak of
    finite steps, and the update is jit-compatible inside a parallel
    train step."""

    def test_scale_state_machine(self):
        from alpa_tpu.model.model_util import DynamicScaleState
        s = DynamicScaleState.create(init_scale=1024.0)
        s = s.replace(growth_interval=2)
        # overflow -> backoff
        s1 = s.update(jnp.bool_(False))
        assert float(s1.scale) == 512.0
        # two finite steps -> growth
        s2 = s1.update(jnp.bool_(True))
        assert float(s2.scale) == 512.0 and int(s2.fine_count) == 1
        s3 = s2.update(jnp.bool_(True))
        assert float(s3.scale) == 1024.0

    def test_scaled_training_step(self):
        from alpa_tpu.model.model_util import (TrainState, all_finite,
                                               cross_entropy_loss)
        cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                        seq_len=16, vocab_size=64, dtype=jnp.bfloat16)
        model = GPTModel(cfg)
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (8, 16), 0, 64)
        params = model.init(rng, ids)
        state = TrainState.create_with_scale(
            apply_fn=model.apply, params=params, tx=optax.sgd(1e-2),
            use_dynamic_scale=True)

        @alpa_tpu.parallelize(method=alpa_tpu.DataParallel(),
                              donate_argnums=())
        def train_step(state, batch):
            ds = state.dynamic_scale

            def loss_fn(p):
                logits = state.apply_fn(p, batch["ids"])
                return cross_entropy_loss(
                    logits.astype(jnp.float32), batch["labels"]) * ds.scale

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
            grads = jax.tree_util.tree_map(lambda g: g / ds.scale, grads)
            finite = all_finite(grads)
            ds2 = ds.update(finite)
            # only apply updates when grads are finite
            new_state = state.apply_gradients(grads=jax.tree_util.tree_map(
                lambda g: jnp.where(finite, g, jnp.zeros_like(g)), grads))
            return new_state.replace(dynamic_scale=ds2), loss / ds.scale

        batch = {"ids": ids,
                 "labels": jax.random.randint(jax.random.PRNGKey(1),
                                              (8, 16), 0, 64)}
        losses = []
        for _ in range(4):
            state, loss = train_step(state, batch)
            losses.append(float(loss))
        assert all(np.isfinite(l) for l in losses)
        assert losses[-1] < losses[0]
        assert float(state.dynamic_scale.scale) >= 1.0
