"""Bloom + CodeGen model families (VERDICT r2 missing#5: serving model
breadth beyond GPT-2/OPT; ref examples/llm_serving/model/bloom_model.py,
codegen_model.py).

Oracle: logits match the transformers implementations on random-init tiny
configs through the params_from_hf weight mapping — this pins down ALiBi,
rotary, the parallel residual, and both checkpoint QKV layouts.  Decode
parity then proves the KV-cache path equals full-context attention.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

torch = pytest.importorskip("torch")

from alpa_tpu.model import bloom_model, codegen_model
from alpa_tpu.model.bloom_model import (BloomConfig, BloomModel,
                                        config_from_bloom_spec,
                                        init_bloom_kv_caches)
from alpa_tpu.model.codegen_model import (CodeGenConfig, CodeGenModel,
                                          config_from_codegen_spec,
                                          init_codegen_kv_caches)


def _tiny_bloom():
    from transformers import BloomConfig as HFConfig
    from transformers import BloomForCausalLM
    torch.manual_seed(0)
    hf_cfg = HFConfig(vocab_size=128, hidden_size=32, n_layer=2, n_head=4,
                      use_cache=False)
    hf = BloomForCausalLM(hf_cfg).eval()
    cfg = BloomConfig(vocab_size=128, hidden_size=32, num_layers=2,
                      num_heads=4, seq_len=24)
    params = bloom_model.params_from_hf(hf, cfg)
    return hf, cfg, params


def _tiny_codegen():
    from transformers import CodeGenConfig as HFConfig
    from transformers import CodeGenForCausalLM
    torch.manual_seed(0)
    hf_cfg = HFConfig(vocab_size=128, n_embd=32, n_layer=2, n_head=4,
                      rotary_dim=8, n_positions=64, use_cache=False)
    hf = CodeGenForCausalLM(hf_cfg).eval()
    cfg = CodeGenConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=4, rotary_dim=8, seq_len=24)
    params = codegen_model.params_from_hf(hf, cfg)
    return hf, cfg, params


class TestBloom:

    def test_matches_transformers(self):
        hf, cfg, params = _tiny_bloom()
        ids = np.array([[1, 5, 9, 2, 7, 3], [4, 4, 8, 1, 0, 6]], np.int64)
        with torch.no_grad():
            expected = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(BloomModel(cfg).apply(params, jnp.asarray(ids)))
        np.testing.assert_allclose(got, expected, rtol=5e-4, atol=5e-4)

    def test_decode_matches_full_context(self):
        _, cfg, params = _tiny_bloom()
        model = BloomModel(cfg)
        rng = np.random.RandomState(0)
        ids = rng.randint(0, cfg.vocab_size, (2, 10)).astype(np.int32)
        full = np.asarray(model.apply(params, jnp.asarray(ids)))

        caches = init_bloom_kv_caches(cfg, 2)
        logits_p, caches = model.apply(params, jnp.asarray(ids[:, :6]),
                                       None, caches)
        np.testing.assert_allclose(np.asarray(logits_p), full[:, :6],
                                   rtol=5e-4, atol=5e-4)
        for t in range(6, 10):
            step, caches = model.apply(params, jnp.asarray(ids[:, t:t + 1]),
                                       None, caches)
            np.testing.assert_allclose(np.asarray(step)[:, 0], full[:, t],
                                       rtol=5e-4, atol=5e-4)

    def test_generator_integration(self):
        """The serving Generator drives BloomModel unchanged (cache-as-
        invars interface parity with GPT)."""
        from alpa_tpu.serve.generation import GenerationConfig, Generator
        _, cfg, params = _tiny_bloom()
        model = BloomModel(cfg)
        gen = Generator.__new__(Generator)
        # Generator's ctor is GPT-typed only in annotations; construct
        # normally to prove the interface really is model-agnostic
        gen.__init__(model, params, cfg, batch_size=1,
                     prompt_buckets=[8, 16])
        out = gen.generate(np.array([1, 2, 3], np.int32),
                           GenerationConfig(max_new_tokens=4))
        assert out.shape[-1] == 7

    def test_spec_ladder(self):
        cfg = config_from_bloom_spec("bloom-176b")
        assert (cfg.hidden_size, cfg.num_layers, cfg.num_heads) == \
            (14336, 70, 112)
        assert bloom_model.alibi_slopes(112).shape == (112,)


class TestCodeGen:

    def test_matches_transformers(self):
        hf, cfg, params = _tiny_codegen()
        ids = np.array([[1, 5, 9, 2, 7, 3], [4, 4, 8, 1, 0, 6]], np.int64)
        with torch.no_grad():
            expected = hf(torch.tensor(ids)).logits.numpy()
        got = np.asarray(CodeGenModel(cfg).apply(params, jnp.asarray(ids)))
        np.testing.assert_allclose(got, expected, rtol=5e-4, atol=5e-4)

    def test_decode_matches_full_context(self):
        _, cfg, params = _tiny_codegen()
        model = CodeGenModel(cfg)
        rng = np.random.RandomState(1)
        ids = rng.randint(0, cfg.vocab_size, (2, 10)).astype(np.int32)
        full = np.asarray(model.apply(params, jnp.asarray(ids)))

        caches = init_codegen_kv_caches(cfg, 2)
        logits_p, caches = model.apply(params, jnp.asarray(ids[:, :6]),
                                       None, caches)
        np.testing.assert_allclose(np.asarray(logits_p), full[:, :6],
                                   rtol=5e-4, atol=5e-4)
        for t in range(6, 10):
            step, caches = model.apply(params, jnp.asarray(ids[:, t:t + 1]),
                                       None, caches)
            np.testing.assert_allclose(np.asarray(step)[:, 0], full[:, t],
                                       rtol=5e-4, atol=5e-4)

    def test_per_row_cache_indices(self):
        """Mixed prompt lengths decode correctly via vector cache indices
        (the continuous-batching engine's contract)."""
        _, cfg, params = _tiny_codegen()
        model = CodeGenModel(cfg)
        rng = np.random.RandomState(2)
        lens = [4, 7]
        ids = rng.randint(0, cfg.vocab_size, (2, 10)).astype(np.int32)
        full = np.asarray(model.apply(params, jnp.asarray(ids)))

        # prefill each row padded to 7, then decode one step per row at
        # its own position
        caches = init_codegen_kv_caches(cfg, 2)
        padded = ids[:, :7].copy()
        padded[0, 4:] = 0
        logits_p, caches = model.apply(params, jnp.asarray(padded), None,
                                       caches)
        caches = [(k, v, jnp.asarray(lens, jnp.int32))
                  for (k, v, _) in caches]
        tok = jnp.asarray(np.stack([ids[0, 4], ids[1, 7]])[:, None])
        step, caches = model.apply(params, tok, None, caches)
        np.testing.assert_allclose(np.asarray(step)[0, 0], full[0, 4],
                                   rtol=5e-4, atol=5e-4)
        np.testing.assert_allclose(np.asarray(step)[1, 0], full[1, 7],
                                   rtol=5e-4, atol=5e-4)

    def test_spec_ladder(self):
        cfg = config_from_codegen_spec("codegen-16b")
        assert (cfg.hidden_size, cfg.num_layers, cfg.num_heads,
                cfg.rotary_dim) == (6144, 34, 24, 64)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
