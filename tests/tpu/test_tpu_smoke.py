"""TPU smoke subset (ref tests/tpu/, SURVEY.md §4.7).

Runs only on a real TPU backend (skipped on the CPU mesh the main suite
uses):

  ALPA_TPU_TEST_ON_TPU=1 python -m pytest tests/tpu/ -q   # on TPU hosts

Unlike the reference — whose TPU support was intra-op-only and partial
(ref shard_parallel/compile_executable.py:83-85 raising NotImplementedError
for TPU grad-acc) — every alpa_tpu path is TPU-first, so this subset just
sanity-runs the core flows on the real chip.
"""
import numpy as np
import pytest

import jax


def _on_tpu():
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:  # pylint: disable=broad-except
        return False


pytestmark = pytest.mark.skipif(not _on_tpu(),
                                reason="requires a real TPU backend")


class TestTpuSmoke:

    def test_shard_parallel_train(self):
        import alpa_tpu
        from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                      get_mlp_train_step)
        state, batch = create_mlp_train_state_and_batch(batch_size=64)
        step = get_mlp_train_step(alpa_tpu.ShardParallel(),
                                  use_value_and_grad=True)
        for _ in range(3):
            state, loss = step(state, batch)
        assert np.isfinite(float(loss))

    def test_grad_accumulation(self):
        import alpa_tpu
        from alpa_tpu.testing import (assert_allclose,
                                      create_mlp_train_state_and_batch,
                                      get_mlp_train_step)
        s_a, batch = create_mlp_train_state_and_batch(batch_size=64)
        s_b, _ = create_mlp_train_state_and_batch(batch_size=64)
        full = get_mlp_train_step(alpa_tpu.ShardParallel(),
                                  use_value_and_grad=True)
        acc = get_mlp_train_step(
            alpa_tpu.ShardParallel(num_micro_batches=4),
            use_value_and_grad=True)
        s_a, la = full(s_a, batch)
        s_b, lb = acc(s_b, batch)
        assert_allclose(float(la), float(lb), 1e-2, 1e-2)

    def test_flash_attention_kernel(self):
        import jax.numpy as jnp

        from alpa_tpu.model.gpt_model import reference_attention
        from alpa_tpu.ops.flash_attention import flash_attention
        ks = jax.random.split(jax.random.PRNGKey(0), 3)
        q, k, v = (jax.random.normal(kk, (2, 512, 8, 64), jnp.bfloat16)
                   for kk in ks)
        out = flash_attention(q, k, v, causal=True)
        ref = reference_attention(q, k, v, causal=True)
        diff = float(jnp.abs(out.astype(jnp.float32) -
                             ref.astype(jnp.float32)).max())
        assert diff < 0.05, diff

    def test_generation(self):
        from alpa_tpu.model.gpt_model import GPTConfig
        from alpa_tpu.serve import GenerationConfig, get_model
        gen = get_model(GPTConfig(hidden_size=64, num_layers=2,
                                  num_heads=4, seq_len=64, vocab_size=128))
        out = gen.generate(np.array([[1, 2, 3]], np.int32),
                           GenerationConfig(max_new_tokens=4))
        assert out.shape == (1, 7)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
