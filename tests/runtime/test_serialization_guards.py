"""Legacy checkpoint guard rails (ISSUE 3 satellites): async-mover
failures must surface from checkpoint_wait(), and corrupt checkpoint
directories must fail validation with a named leaf — not a stray shape
error deep inside restore."""
import json
import os
import shutil

import numpy as np
import pytest

from alpa_tpu import serialization
from alpa_tpu.serialization import (CheckpointCorruptError, _AsyncMover,
                                    checkpoint_wait, restore_checkpoint,
                                    save_checkpoint, validate_checkpoint)


def _state():
    return {"w": np.arange(8, dtype=np.float32),
            "b": np.ones((2, 3), np.float32)}


class TestAsyncMoverFailures:

    def test_failure_raises_from_wait_and_cleans_partial(self, tmp_path):
        mover = _AsyncMover()
        src = tmp_path / "src_leaf"
        dst = tmp_path / "final" / "leaf"
        src.mkdir()
        (src / "shard_p0_0.npy").write_bytes(b"x" * 16)
        (src / "shard_p0_1.npy").write_bytes(b"y" * 16)

        real_move = _AsyncMover._move

        def dying_move(s, d):
            # copy half, then die — leaves a partial destination
            os.makedirs(d, exist_ok=True)
            shutil.copy(os.path.join(s, "shard_p0_0.npy"),
                        os.path.join(d, "shard_p0_0.npy"))
            raise OSError("NFS went away")

        mover._move = dying_move
        mover.submit(str(src), str(dst))
        with pytest.raises(CheckpointCorruptError, match="NFS went away"):
            mover.wait()
        # the partial leaf dir was removed: it cannot masquerade as a
        # complete checkpoint on the shared FS
        assert not dst.exists()
        # the error was consumed; the mover keeps working
        mover._move = real_move
        mover.submit(str(src), str(dst))
        mover.wait()
        assert sorted(os.listdir(dst)) == ["shard_p0_0.npy",
                                           "shard_p0_1.npy"]

    def test_save_with_cache_dir_surfaces_drain_failure(
            self, tmp_path, monkeypatch):
        calls = []
        real_move = _AsyncMover._move

        def boom_first(src, dst):
            calls.append(src)
            if len(calls) == 1:
                raise OSError("disk full")
            return real_move(src, dst)

        monkeypatch.setattr(_AsyncMover, "_move",
                            staticmethod(boom_first))
        save_checkpoint(str(tmp_path / "final"), _state(), step=1,
                        local_cache_dir=str(tmp_path / "cache"))
        with pytest.raises(CheckpointCorruptError, match="disk full"):
            checkpoint_wait()
        # a second wait is clean (errors are one-shot)
        checkpoint_wait()


class TestValidateCheckpoint:

    def _save(self, tmp_path):
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(ckpt, _state(), step=1)
        return ckpt

    def test_happy_path(self, tmp_path):
        ckpt = self._save(tmp_path)
        validate_checkpoint(ckpt)                       # no raise
        restored = restore_checkpoint(ckpt, _state())
        np.testing.assert_array_equal(restored["w"],
                                      np.arange(8, dtype=np.float32))

    def test_missing_leaf_dir(self, tmp_path):
        ckpt = self._save(tmp_path)
        shutil.rmtree(os.path.join(ckpt, "w"))
        with pytest.raises(CheckpointCorruptError,
                           match="missing leaf directory"):
            restore_checkpoint(ckpt, _state())

    def test_missing_shard_file(self, tmp_path):
        ckpt = self._save(tmp_path)
        os.unlink(os.path.join(ckpt, "w", "shard_p0_0.npy"))
        with pytest.raises(CheckpointCorruptError,
                           match="missing or empty"):
            restore_checkpoint(ckpt, _state())

    def test_empty_shard_file(self, tmp_path):
        ckpt = self._save(tmp_path)
        open(os.path.join(ckpt, "w", "shard_p0_0.npy"), "w").close()
        with pytest.raises(CheckpointCorruptError,
                           match="missing or empty"):
            validate_checkpoint(ckpt)

    def test_empty_index(self, tmp_path):
        ckpt = self._save(tmp_path)
        with open(os.path.join(ckpt, "w", "index_p0.json"), "w") as f:
            json.dump([], f)
        with pytest.raises(CheckpointCorruptError,
                           match="no usable index"):
            validate_checkpoint(ckpt)

    def test_out_of_bounds_slice(self, tmp_path):
        ckpt = self._save(tmp_path)
        idx = os.path.join(ckpt, "w", "index_p0.json")
        with open(idx) as f:
            index = json.load(f)
        index[0]["slice"] = [[0, 16]]                  # leaf shape is (8,)
        with open(idx, "w") as f:
            json.dump(index, f)
        with pytest.raises(CheckpointCorruptError, match="outside"):
            validate_checkpoint(ckpt)

    def test_coverage_hole(self, tmp_path):
        ckpt = self._save(tmp_path)
        idx = os.path.join(ckpt, "w", "index_p0.json")
        with open(idx) as f:
            index = json.load(f)
        index[0]["slice"] = [[0, 4]]                   # half the leaf
        with open(idx, "w") as f:
            json.dump(index, f)
        with pytest.raises(CheckpointCorruptError, match="cover"):
            validate_checkpoint(ckpt)

    def test_metadata_missing(self, tmp_path):
        (tmp_path / "empty").mkdir()
        with pytest.raises(CheckpointCorruptError,
                           match="no metadata.json"):
            restore_checkpoint(str(tmp_path / "empty"), _state())

    def test_metadata_truncated_json(self, tmp_path):
        ckpt = self._save(tmp_path)
        with open(os.path.join(ckpt, "metadata.json"), "w") as f:
            f.write('{"step": 1, "leav')
        with pytest.raises(CheckpointCorruptError, match="unreadable"):
            restore_checkpoint(ckpt, _state())

    def test_metadata_wrong_structure(self, tmp_path):
        ckpt = self._save(tmp_path)
        with open(os.path.join(ckpt, "metadata.json"), "w") as f:
            json.dump({"step": 1}, f)
        with pytest.raises(CheckpointCorruptError, match="leaves"):
            restore_checkpoint(ckpt, _state())
