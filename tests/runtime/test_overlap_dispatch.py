"""Overlap-aware pipeshard dispatch (ISSUE 4 tentpole).

Oracle 1: numerics — overlap mode must be bit-identical to the
sequential interpreter AND the synchronous register replay over donated
train steps (same RUN executables, same transfers; only launch timing
differs).  Oracle 2: the dataflow-graph replay itself — a seeded
randomized-topology fuzz drives arbitrary RUN/RESHARD/FREE programs
through :func:`schedule_overlap` and asserts the replay never issues an
op before its producers retired, never frees/overwrites a slot a live
transfer still uses, and never exceeds the in-flight window.
"""
import json
import os
import random

import numpy as np
import pytest

import alpa_tpu
import jax
from alpa_tpu import PipeshardParallel
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
from alpa_tpu.pipeline_parallel.runtime_emitter import (
    DataflowNode, InstructionDataflowGraph, schedule_overlap)
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _restore_dispatch_mode():
    prev = global_config.pipeline_dispatch_mode
    yield
    global_config.pipeline_dispatch_mode = prev


def _run_steps(mode, n_steps=3):
    global_config.pipeline_dispatch_mode = mode
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=4),
        stage_option=UniformStageOption(num_stages=4))
    step = get_mlp_train_step(method, use_value_and_grad=False)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)
    val = None
    for _ in range(n_steps):
        state, val = step(state, batch)
    return state, val, step.get_last_executable()


# ---------------------------------------------------------------------
# end-to-end numerics
# ---------------------------------------------------------------------

def test_overlap_matches_interpreter_and_registers_bitwise():
    alpa_tpu.init("local")
    state_s, val_s, ex_s = _run_steps("sequential")
    state_r, val_r, ex_r = _run_steps("registers")
    state_o, val_o, ex_o = _run_steps("overlap")
    assert ex_s.last_dispatch_stats["mode"] == "sequential"
    assert ex_r.last_dispatch_stats["mode"] == "registers"
    assert ex_o.last_dispatch_stats["mode"] == "overlap"
    leaves_s = jax.tree_util.tree_leaves(state_s.params)
    leaves_r = jax.tree_util.tree_leaves(state_r.params)
    leaves_o = jax.tree_util.tree_leaves(state_o.params)
    assert len(leaves_s) == len(leaves_o) > 0
    for a, b in zip(leaves_s, leaves_o):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(leaves_r, leaves_o):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(val_s), np.asarray(val_o))
    np.testing.assert_array_equal(np.asarray(val_r), np.asarray(val_o))


def test_overlap_stats_shape():
    alpa_tpu.init("local")
    _, _, ex = _run_steps("overlap", n_steps=2)
    st = ex.last_dispatch_stats
    assert st["mode"] == "overlap"
    assert st["n_cross_mesh"] > 0
    assert 0 < st["n_launches"] <= st["n_cross_mesh"]
    assert 0 <= st["n_hoisted"] <= st["n_cross_mesh"]
    assert st["overlap_window"] >= 1
    assert 0.0 <= st["overlap_fraction"] <= 1.0
    assert st["transfer_busy_s"] >= 0.0
    assert st["wait_blocked_s"] >= 0.0
    # the two lowered modes share slot numbering (phase 1 is mode-free)
    ovl = ex._register_programs["overlap"]
    reg = ex._ensure_lowered("registers")
    assert ovl.slot_of == reg.slot_of
    assert ovl.n_instructions == reg.n_instructions
    assert ovl.graph is not None and reg.graph is not None
    assert ovl.graph.preds == reg.graph.preds


def test_overlap_stays_on_graph_executor_when_tracing():
    """Trace collection no longer forces the interpreter: spans are
    compiled into the replay plan as per-node hooks (ISSUE 6), so
    overlap keeps running on the graph executor with the trace hook
    reported in its dispatch stats."""
    alpa_tpu.init("local")
    prev = global_config.collect_trace
    global_config.collect_trace = True
    try:
        _, _, ex = _run_steps("overlap", n_steps=1)
        st = ex.last_dispatch_stats
        assert st["mode"] == "overlap"
        assert "trace" in st["hooks"]
    finally:
        global_config.collect_trace = prev


def test_overlap_debug_dump_counters():
    from alpa_tpu.monitoring import format_overlap_report, get_overlap_stats
    alpa_tpu.init("local")
    _, _, _ = _run_steps("overlap", n_steps=1)
    stats = get_overlap_stats()
    assert stats["runtime"]["steps"] >= 1
    assert stats["runtime"]["n_launches"] >= 1
    assert stats["planner"]["plans"] >= 0
    report = format_overlap_report()
    assert "overlap dispatch" in report
    assert "resharding planner" in report


# ---------------------------------------------------------------------
# randomized-topology fuzz of the graph replay (seeded)
# ---------------------------------------------------------------------

def _random_program(rng, n_ops):
    """A random SSA-style RUN/RESHARD/FREE program over integer slots."""
    nodes = []
    live = []
    next_slot = [0]

    def new_slot():
        s = next_slot[0]
        next_slot[0] += 1
        return s

    for idx in range(n_ops):
        c = rng.random()
        if not live or c < 0.45:
            k = min(len(live), rng.randrange(0, 3))
            reads = tuple(rng.sample(live, k)) if k else ()
            kills = ()
            if reads and rng.random() < 0.3:
                kills = (reads[rng.randrange(len(reads))],)
                for s in kills:
                    live.remove(s)
            writes = tuple(new_slot() for _ in range(rng.randrange(1, 3)))
            live.extend(writes)
            nodes.append(DataflowNode(idx, "RUN", reads=reads,
                                      writes=writes, kills=kills))
        elif c < 0.85:
            src = rng.choice(live)
            dst = new_slot()
            live.append(dst)
            edge = (rng.randrange(4), rng.randrange(4))
            nodes.append(DataflowNode(idx, "RESHARD", reads=(src,),
                                      writes=(dst,), edge=edge,
                                      cross_mesh=edge[0] != edge[1]))
        else:
            k = rng.randrange(1, min(3, len(live)) + 1)
            slots = tuple(rng.sample(live, k))
            for s in slots:
                live.remove(s)
            nodes.append(DataflowNode(idx, "FREE", kills=slots))
    return nodes


def _replay_and_check(nodes, graph, plan, window):
    """Simulate a schedule_overlap plan, asserting every replay
    invariant the real executor relies on."""
    issued, retired = set(), set()
    inflight = []
    for kind, i in plan:
        node = nodes[i]
        if kind in ("exec", "launch"):
            assert i not in issued, f"double issue of node {i}"
            for p in graph.preds[i]:
                assert p in retired, \
                    f"{kind} {i} before pred {p} retired (seed case)"
            issued.add(i)
        if kind == "exec":
            # no live transfer may still be using a slot this op
            # overwrites, frees, or (for writes) reads from
            touched = set(node.writes) | set(node.kills)
            for t in inflight:
                tn = nodes[t]
                assert not (set(tn.reads) & touched), \
                    f"exec {i} kills/overwrites slot a live transfer " \
                    f"{t} reads"
                assert not (set(tn.writes) &
                            (touched | set(node.reads))), \
                    f"exec {i} touches slot a live transfer {t} writes"
            retired.add(i)
        elif kind == "launch":
            assert node.cross_mesh, "only cross-mesh RESHARDs launch"
            inflight.append(i)
            assert len(inflight) <= window, "in-flight window exceeded"
        else:  # wait
            assert i in inflight, f"wait for non-inflight {i}"
            inflight.remove(i)
            retired.add(i)
    assert not inflight, "transfers left unwaited at end of plan"
    assert issued == set(range(len(nodes))), "nodes never issued"
    # non-transfer ops keep their flat relative order
    execs = [i for k, i in plan if k == "exec"]
    assert execs == sorted(execs)


def test_fuzz_graph_replay_invariants():
    for seed in range(25):
        rng = random.Random(1234 + seed)
        nodes = _random_program(rng, n_ops=40)
        graph = InstructionDataflowGraph.build(nodes)
        for window in (1, 2, 3, 5):
            plan, n_hoisted = schedule_overlap(graph, window)
            _replay_and_check(nodes, graph, plan, window)
            assert 0 <= n_hoisted <= graph.n_cross_mesh


def test_graph_edges_cover_donation_hazard():
    """A donating RUN must depend on every transfer reading the donated
    slot — the cross-thread hazard overlap mode introduces."""
    nodes = [
        DataflowNode(0, "RUN", writes=(0,)),
        DataflowNode(1, "RESHARD", reads=(0,), writes=(1,), edge=(0, 1),
                     cross_mesh=True),
        DataflowNode(2, "RUN", reads=(0,), writes=(2,), kills=(0,)),
        DataflowNode(3, "FREE", kills=(1,)),
    ]
    g = InstructionDataflowGraph.build(nodes)
    assert 1 in g.preds[2]          # donation waits for the transfer
    assert 1 in g.preds[3]          # FREE waits for the transfer's write
    plan, _ = schedule_overlap(g, 4)
    pos = {(k, i): p for p, (k, i) in enumerate(plan)}
    assert pos[("wait", 1)] < pos[("exec", 2)]


# ---------------------------------------------------------------------
# dispatch regression vs the committed artifact (ISSUE 4 satellite)
# ---------------------------------------------------------------------

def test_overlap_dispatch_no_regression_vs_artifact():
    """Replay the committed bench payload in overlap mode and fail if
    per-instruction overhead regressed >2x vs the committed artifact.

    A single timed replay is at the mercy of scheduler noise on a
    loaded CI host, so take the best of three — a regression has to
    reproduce in every replay to fail the gate."""
    path = os.path.join(REPO, "benchmark", "results",
                        "dispatch_modes.json")
    with open(path, encoding="utf-8") as f:
        artifact = json.load(f)
    committed = artifact["modes"].get("overlap")
    assert committed is not None, \
        "dispatch_modes.json artifact predates overlap mode — " \
        "regenerate with benchmark/bench_dispatch.py"
    from scripts.dispatch_overhead_bench import measure
    stats = min((measure(n_steps=5, dispatch_mode="overlap")
                 for _ in range(3)),
                key=lambda s: s["per_inst_us"])
    assert stats["mode"] == "overlap"
    assert stats["per_inst_us"] < 2.0 * committed["per_inst_us"], (
        stats, committed)
