"""Analytic ICI/DCN alpha-beta defaults per TPU generation (VERDICT r2
next #8): where the single-chip rig leaves the collective tables empty,
published link constants back the stage DP's comm terms instead of
abstract placeholders.
"""
import os

import numpy as np
import pytest

from alpa_tpu.mesh_profiling import (COLLECTIVE_KINDS, TPU_GENERATION_SPECS,
                                     analytic_calibration,
                                     calibration_from_file,
                                     detect_tpu_generation,
                                     get_effective_calibration,
                                     merge_calibrations)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def test_analytic_covers_all_kinds_and_generations():
    for gen in TPU_GENERATION_SPECS:
        cal = analytic_calibration(gen)
        for kind in COLLECTIVE_KINDS:
            alpha, beta = cal.alpha_beta(kind)
            assert alpha > 0 and beta > 0
        assert cal.sec_per_flop(1e12) > 0
    # generation ordering: faster fabric -> smaller beta; faster MXU ->
    # smaller sec/flop
    assert (analytic_calibration("v5p").alpha_beta("all_reduce")[1] <
            analytic_calibration("v5e").alpha_beta("all_reduce")[1])
    assert (analytic_calibration("v5p").sec_per_flop(1e12) <
            analytic_calibration("v5e").sec_per_flop(1e12))
    # DCN fabric is slower than ICI
    ici = analytic_calibration("v5e", "ici").alpha_beta("all_gather")
    dcn = analytic_calibration("v5e", "dcn").alpha_beta("all_gather")
    assert dcn[0] > ici[0] and dcn[1] > ici[1]


def test_detect_generation_prefers_env(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v5p")
    assert detect_tpu_generation() == "v5p"
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "bogus-gen")
    assert detect_tpu_generation(default="v4") in TPU_GENERATION_SPECS


def test_merge_measured_wins_analytic_fills():
    tpu_db = os.path.join(REPO, "prof_database_tpu.json")
    if not os.path.exists(tpu_db):
        pytest.skip("no TPU profiling DB checked in")
    measured = calibration_from_file(tpu_db)
    assert measured is not None
    # the single-chip DB has dots but (r2 weak #4) no collectives
    merged = merge_calibrations(measured, analytic_calibration("v5e"))
    assert merged.dot_points == measured.dot_points  # measured dots kept
    for kind in COLLECTIVE_KINDS:
        assert merged.alpha_beta(kind) is not None  # analytic filled
    # merged calibration makes a TPU logical mesh fully calibrated
    from alpa_tpu.device_mesh import LogicalDeviceMesh
    mesh = LogicalDeviceMesh(None, np.arange(8).reshape(1, 8),
                             calibration=merged)
    assert mesh.calibrated
    # a 1 MB all-reduce over an 8-wide v5e ICI axis: ring cost in real
    # seconds, order tens of microseconds
    cost = mesh.all_reduce_cost(1 << 20, 1)
    assert 1e-6 < cost < 1e-2, cost


def test_cpu_measured_fits_match_analytic_form():
    """The CPU-mesh measured collective fits follow the analytic
    t = alpha + beta * bytes form: nonnegative alpha, positive beta,
    monotone in size."""
    cpu_db = os.path.join(REPO, "prof_database_cpu8.json")
    if not os.path.exists(cpu_db):
        pytest.skip("no CPU profiling DB checked in")
    cal = calibration_from_file(cpu_db)
    assert cal is not None and cal.collective_ab
    for kind, (alpha, beta) in cal.collective_ab.items():
        assert alpha >= 0 and beta > 0, (kind, alpha, beta)
        assert alpha + beta * 2e6 > alpha + beta * 1e6


def test_effective_calibration_platform_gate():
    # non-TPU platforms get the measured DB untouched (possibly None)
    cal_cpu = get_effective_calibration(platform="cpu")
    # TPU platforms always come back with a full collective table
    cal_tpu = get_effective_calibration(platform="axon")
    assert cal_tpu is not None
    for kind in COLLECTIVE_KINDS:
        assert cal_tpu.alpha_beta(kind) is not None
    if cal_cpu is not None:
        assert set(cal_cpu.collective_ab) <= set(cal_tpu.collective_ab)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
