"""Seeded kill-schedule fuzz for the elastic supervisor (ISSUE 16):
kills and preemptions injected at randomized step boundaries AND
randomized instruction boundaries across >=20 seeds on the committed
2-stage pipeshard fixture, plus one dp=4->dp=2 mid-run rescale.

Every schedule must satisfy the same two invariants:

* bounded recovery — each seed's episodes all replay at most
  ``elastic_step_budget`` committed steps;
* loss-curve continuity — every committed step's loss is **bitwise
  equal** to the uninterrupted run of the same compiled executable
  (the supervisor reuses the memoized plan, so recovery must be
  invisible in the curve, not merely close).

The solve hook is memoized per device set: all 20+ schedules share ONE
pipeshard compile, so the whole sweep costs steps, not compiles.
"""
import random
import time

import jax
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu import elastic, fault
from alpa_tpu.checkpoint.manager import CheckpointManager
from alpa_tpu.device_mesh import VirtualPhysicalMesh
from alpa_tpu.elastic import (ElasticSupervisor, PreemptionNotice,
                              WorkerLost)
from alpa_tpu.pipeline_parallel.layer_construction import ManualLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import create_mlp_train_state_and_batch, \
    get_mlp_train_step

pytestmark = pytest.mark.fault

N_SEEDS = 20
N_STEPS = 4
# stage_launch fires ~8x per step on this fixture (2 stages x 2
# microbatches x fwd/bwd); 0..23 lands the kill inside steps 0-2 at an
# arbitrary instruction boundary
MAX_INSTRUCTION_OFFSET = 23


@pytest.fixture(autouse=True)
def _clean_elastic_state():
    yield
    fault.set_escalation_manager(None)
    elastic._ACTIVE = None


@pytest.fixture(autouse=True)
def _reset_ckpt_metrics():
    from alpa_tpu.checkpoint import metrics
    yield
    metrics.reset()


def _schedule(rng):
    """One randomized kill schedule: what to inject, and where."""
    kind = rng.choice(["kill_boundary", "preempt_boundary",
                       "kill_instruction"])
    if kind == "kill_boundary":
        return kind, fault.FaultSpec(
            "worker_lost", times=1, after=rng.randrange(N_STEPS),
            exc=lambda: WorkerLost())
    if kind == "preempt_boundary":
        return kind, fault.FaultSpec(
            "preemption_notice", times=1, after=rng.randrange(N_STEPS),
            exc=lambda: PreemptionNotice(grace_s=30.0))
    return kind, fault.FaultSpec(
        "stage_launch", times=1,
        after=rng.randrange(MAX_INSTRUCTION_OFFSET + 1))


def test_kill_schedule_fuzz(tmp_path):
    alpa_tpu.init(cluster="local")
    cache = {}

    def solve(devices):
        key = tuple(id(d) for d in devices)
        if key not in cache:
            n = len(devices)
            vm = VirtualPhysicalMesh(
                1, n, np.array(list(devices), dtype=object).reshape(1, n))
            method = alpa_tpu.PipeshardParallel(
                devices=vm, num_micro_batches=2,
                layer_option=ManualLayerOption(),
                stage_option=UniformStageOption(num_stages=2))
            cache[key] = get_mlp_train_step(method,
                                            use_value_and_grad=True)
        return cache[key]

    def fresh_state_and_batch():
        return create_mlp_train_state_and_batch(
            batch_size=64, num_layers=4, manual_pipeline_layer=True)

    # ONE uninterrupted baseline curve from the shared executable
    state, batch = fresh_state_and_batch()
    base_step = solve(list(jax.devices()))
    base_losses = []
    for _ in range(N_STEPS):
        state, loss = base_step(state, batch)
        base_losses.append(np.asarray(loss))

    kinds_seen = set()
    for seed in range(N_SEEDS):
        rng = random.Random(seed)
        kind, spec = _schedule(rng)
        kinds_seen.add(kind)
        state, _ = fresh_state_and_batch()
        sup = ElasticSupervisor(
            solve, state, checkpoint_root=str(tmp_path / f"s{seed}"),
            register_globally=False)
        losses = {}
        with fault.FaultPlan(spec) as plan:
            for _ in range(40):
                if sup.step_index >= N_STEPS:
                    break
                loss = sup.step(batch)
                losses[sup.step_index] = np.asarray(loss)
            else:
                raise AssertionError(
                    f"seed {seed} ({kind}): stuck at "
                    f"step {sup.step_index}")
        assert plan.fired(spec.site) == 1, (seed, kind)
        assert len(sup.episodes) == 1, (seed, kind, sup.episodes)
        ep = sup.episodes[0]
        assert ep["within_step_budget"], (seed, kind, ep)
        assert ep["replan"] == "reused", (seed, kind, ep)
        if kind == "kill_instruction":
            # mid-step: torn state must never have been snapshotted
            assert ep["mid_step"] is True, (seed, kind, ep)
            assert ep["snapshot"] == "skipped", (seed, kind, ep)
        for i in range(1, N_STEPS + 1):
            assert np.array_equal(losses[i], base_losses[i - 1]), (
                f"seed {seed} ({kind}): loss diverged at step {i}: "
                f"{losses[i]!r} != {base_losses[i - 1]!r}")

    # the sweep must actually have exercised both boundary kinds and
    # the instruction-boundary kind — a fuzzer that collapsed to one
    # schedule class proves nothing
    assert kinds_seen == {"kill_boundary", "preempt_boundary",
                          "kill_instruction"}, kinds_seen


def test_fuzz_includes_dp4_to_dp2_rescale(tmp_path):
    """The satellite's required mid-run rescale: ZeRO-2 dp=4 training
    killed down to dp=2, shards reassembled bitwise through
    ``ShardStore.read_leaf_slice`` on restore, loss curve bitwise vs
    an uninterrupted dp=2 run restored from the same step."""
    alpa_tpu.init(cluster="local")
    cache = {}

    def solve(devices):
        key = tuple(id(d) for d in devices)
        if key not in cache:
            method = alpa_tpu.Zero2Parallel(devices=list(devices))
            cache[key] = get_mlp_train_step(method,
                                            use_value_and_grad=True)
        return cache[key]

    state, batch = create_mlp_train_state_and_batch(16, hidden_dim=64)
    sup = ElasticSupervisor(solve, state, checkpoint_root=str(tmp_path),
                            devices=jax.devices()[:4],
                            register_globally=False)
    survivors = list(jax.devices()[:2])
    with fault.FaultPlan(fault.FaultSpec(
            "worker_lost", times=1, after=2,
            exc=lambda: WorkerLost(survivors=survivors))):
        losses = {}
        for _ in range(40):
            if sup.step_index >= 5:
                break
            loss = sup.step(batch)
            losses[sup.step_index] = np.asarray(loss)

    ep = sup.episodes[0]
    assert ep["replan"] == "accepted", ep
    assert ep["devices_before"] == 4 and ep["devices_after"] == 2
    assert ep["within_step_budget"], ep

    r = ep["restored_step"]
    mgr = CheckpointManager(str(tmp_path), async_save=False)
    c_state, _ = create_mlp_train_state_and_batch(16, hidden_dim=64)
    c_state = mgr.restore(c_state, step=r)
    c_step = solve(survivors)
    for i in range(r + 1, 6):
        c_state, c_loss = c_step(c_state, batch)
        assert np.array_equal(losses[i], np.asarray(c_loss)), (
            f"dp rescale: loss diverged at step {i}")
