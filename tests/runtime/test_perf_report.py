"""Step perf analysis engine + regression gate (ISSUE 9 tentpole).

Oracle 1: on the committed synthetic 2-mesh 4-microbatch fixture trace
(known durations), the measured critical path, per-mesh bubble
fractions, and the queue-wait/wire transfer split are pinned exactly,
and per-mesh fractions sum to 1.  Oracle 2: the what-if re-simulator is
monotone — zeroing ops never increases the makespan, and zeroing an
off-critical-path op never beats zeroing an on-path op.  Oracle 3: the
MFU formula against hand-computed FLOPs.  Oracle 4: the perf gate
passes on the committed baseline and fails loudly on an injected 2×
regression.  Oracle 5 (end-to-end): a live traced overlap step yields a
graph-joined report, the three gauges, and ``perf_report.txt``.
"""
import copy
import json
import os

import pytest

import alpa_tpu
from alpa_tpu.analysis.critical_path import (TimedOp, longest_path,
                                             measured_critical_path,
                                             simulate_dag, whatif)
from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry import metrics as tmetrics
from alpa_tpu.telemetry import perf
from alpa_tpu.telemetry import trace as ttrace
from alpa_tpu.telemetry.trace import TraceRecorder

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
FIXTURE = os.path.join(REPO, "benchmark", "results",
                       "perf_gate_fixture_trace.json")
BASELINE = os.path.join(REPO, "benchmark", "results",
                        "perf_gate_baseline.json")


@pytest.fixture
def fresh_trace():
    """Fresh recorder + tracing on; restores both afterwards."""
    rec = TraceRecorder()
    old_rec = ttrace.set_recorder(rec)
    prev = ttrace.set_enabled(True)
    yield rec
    ttrace.set_enabled(prev)
    ttrace.set_recorder(old_rec)


def _load_fixture():
    with open(FIXTURE, encoding="utf-8") as f:
        return json.load(f)


# ---------------------------------------------------------------------
# critical-path walk + DAG re-simulation (pure data layer)
# ---------------------------------------------------------------------

class TestCriticalPath:

    OPS = [
        TimedOp(0, "RUN s0", "exec", "mesh 0", 0.0, 100.0),
        TimedOp(1, "LAUNCH r", "launch", "mesh 0", 100.0, 105.0),
        TimedOp(2, "WAIT r", "wait", "mesh 1", 105.0, 150.0),
        TimedOp(3, "RUN s1", "exec", "mesh 1", 150.0, 260.0),
    ]
    PREDS = {1: [0], 2: [1], 3: [2]}

    def test_walk_spans_envelope_with_causal_edges(self):
        cp = measured_critical_path(self.OPS, self.PREDS)
        assert [s.op.idx for s in cp.steps] == [0, 1, 2, 3]
        assert cp.total_us == 260.0
        assert cp.gap_us == 0.0
        assert cp.coverage == pytest.approx(1.0)
        # vias: first op is the walk start, the rest causal
        assert cp.steps[0].via == "start"
        assert all(s.via == "dep" for s in cp.steps[1:])
        assert sum(s.share for s in cp.steps) == pytest.approx(1.0)

    def test_gap_attribution(self):
        ops = [
            TimedOp(0, "RUN a", "exec", "mesh 0", 0.0, 100.0),
            TimedOp(1, "RUN b", "exec", "mesh 0", 130.0, 200.0),
        ]
        cp = measured_critical_path(ops, {1: [0]})
        assert cp.steps[1].gap_us == pytest.approx(30.0)
        assert cp.total_us + cp.gap_us == pytest.approx(cp.envelope_us)

    def test_issue_order_fallback_binds_without_graph(self):
        # concurrent tracks, no causal edges: the walk still spans the
        # envelope via the latest-earlier-finisher fallback
        cp = measured_critical_path(self.OPS, {})
        assert cp.steps[-1].op.idx == 3
        assert cp.total_us + cp.gap_us >= 0.95 * cp.envelope_us

    def test_simulate_matches_hand_makespan(self):
        durs = [o.dur_us for o in self.OPS]
        makespan, finish = simulate_dag(durs, [[], [0], [1], [2]])
        assert makespan == 260.0
        assert finish == [100.0, 105.0, 150.0, 260.0]
        length, path = longest_path(durs, [[], [0], [1], [2]])
        assert length == 260.0 and path == [0, 1, 2, 3]

    def test_whatif_monotone_and_onpath_beats_offpath(self):
        # chain A(100)->B(100)->C(100); D(10) dangles off-path
        durs = [100.0, 100.0, 100.0, 10.0]
        preds = [[], [0], [1], [0]]
        baseline, _ = simulate_dag(durs, preds)
        assert baseline == 300.0
        zero_onpath = whatif(durs, preds, {1})
        zero_offpath = whatif(durs, preds, {3})
        assert zero_onpath <= baseline and zero_offpath <= baseline
        # zeroing the off-path op never beats zeroing the on-path op
        assert zero_offpath >= zero_onpath
        assert zero_onpath == 200.0 and zero_offpath == 300.0
        # zeroing everything floors at 0
        assert whatif(durs, preds, {0, 1, 2, 3}) == 0.0


# ---------------------------------------------------------------------
# committed fixture trace: pinned report numbers
# ---------------------------------------------------------------------

class TestFixtureReport:

    def test_pinned_critical_path_and_envelope(self):
        report = perf.report_from_trace(_load_fixture())
        assert report is not None
        assert report.n_ops == 16
        assert report.envelope_us == pytest.approx(600.0)
        # acceptance: path total within 5% of the measured envelope
        assert report.critical_path.total_us == pytest.approx(596.0)
        assert report.critical_path.coverage >= 0.95
        # the path is the mesh-1 RUN chain seeded by mesh 0's first RUN
        top = report.critical_path.top(4)
        assert all(s.op.name.startswith("RUN stage_1") for s in top)

    def test_pinned_bubble_fractions_sum_to_one(self):
        report = perf.report_from_trace(_load_fixture())
        assert set(report.bubbles) == {"mesh 0", "mesh 1"}
        m0, m1 = report.bubbles["mesh 0"], report.bubbles["mesh 1"]
        assert m0.bubble_fraction == pytest.approx(0.30, abs=1e-6)
        assert m0.busy_us == pytest.approx(420.0)
        assert m1.warmup_us == pytest.approx(105.0)
        assert m1.drain_us == pytest.approx(4.0)
        assert m1.stream_wait_us == pytest.approx(11.0)
        for b in report.bubbles.values():
            fr = b.fractions()
            assert sum(fr.values()) == pytest.approx(1.0, abs=1e-6)
            assert 1.0 - fr["busy"] == pytest.approx(b.bubble_fraction)

    def test_pinned_transfer_split(self):
        t = perf.report_from_trace(_load_fixture()).transfers
        # 4 transfers x 7us wire + 1us queue-wait; 5+2+2+2 exposed WAITs
        assert t.wire_us == pytest.approx(28.0)
        assert t.queue_wait_us == pytest.approx(4.0)
        assert t.pool_busy_us == pytest.approx(28.0)
        assert t.exposed_wait_us == pytest.approx(11.0)
        assert t.hidden_us == pytest.approx(17.0)
        assert t.overlap_fraction == pytest.approx(1.0 - 11.0 / 28.0)

    def test_whatif_reshard_on_report(self):
        report = perf.report_from_trace(_load_fixture())
        verdict = report.whatif("reshard")
        assert verdict["n_zeroed"] == 8          # 4 LAUNCH + 4 WAIT
        assert 0.0 <= verdict["saving_fraction"] < 1.0
        assert verdict["whatif_us"] <= verdict["baseline_us"]
        # zeroing the RUNs saves more than zeroing the transfers
        assert report.whatif("run")["saving_us"] >= verdict["saving_us"]

    def test_format_text_and_dict_roundtrip(self):
        report = perf.report_from_trace(_load_fixture())
        text = report.format_text()
        assert "critical path" in text and "per-mesh bubbles" in text
        d = report.to_dict()
        json.dumps(d)  # serializable
        assert d["critical_path_us"] == pytest.approx(596.0)


# ---------------------------------------------------------------------
# MFU formula (the single source bench.py / mfu_breakdown.py ride)
# ---------------------------------------------------------------------

class TestMfu:

    def test_stage_flops_matches_hand_computed_matmul(self):
        import jax
        import jax.numpy as jnp
        w = jnp.ones((16, 4), jnp.float32)
        closed = jax.make_jaxpr(lambda x: x @ w)(
            jnp.ones((8, 16), jnp.float32))
        # dot_general: 2 * prod(out.shape) * contracted = 2*8*4*16
        assert perf.stage_flops(closed) == pytest.approx(1024.0)

    def test_stage_flops_tiny_mlp_dominated_by_matmuls(self):
        import jax
        import jax.numpy as jnp
        w1 = jnp.ones((16, 32), jnp.float32)
        w2 = jnp.ones((32, 4), jnp.float32)

        def mlp(x):
            return jnp.maximum(x @ w1, 0.0) @ w2

        closed = jax.make_jaxpr(mlp)(jnp.ones((8, 16), jnp.float32))
        matmuls = 2 * 8 * 32 * 16 + 2 * 8 * 4 * 32   # 8192 + 2048
        got = perf.stage_flops(closed)
        assert matmuls <= got <= matmuls * 1.2       # + relu elementwise

    def test_knob_overrides_generation_peak(self):
        prev = global_config.device_peak_tflops
        try:
            global_config.device_peak_tflops = 123.0
            assert perf.device_peak_tflops() == 123.0
            assert perf.peak_flops_info()["peak_bf16_tflops"] == 123.0
            assert perf.compute_mfu(61.5) == pytest.approx(0.5)
        finally:
            global_config.device_peak_tflops = prev

    def test_default_peak_comes_from_generation_specs(self):
        from alpa_tpu.mesh_profiling import (TPU_GENERATION_SPECS,
                                             detect_tpu_generation)
        prev = global_config.device_peak_tflops
        try:
            global_config.device_peak_tflops = 0.0
            info = perf.peak_flops_info()
            gen = detect_tpu_generation()
            assert info["generation"] == gen
            assert info["peak_bf16_tflops"] == \
                TPU_GENERATION_SPECS[gen]["peak_bf16_tflops"]
        finally:
            global_config.device_peak_tflops = prev

    def test_mfu_from_time(self):
        # 1e12 FLOPs in 1 s on 1 chip = 1 TFLOPS; peak 2 -> MFU 0.5
        assert perf.mfu_from_time(1e12, 1.0, 1, 2.0) == \
            pytest.approx(0.5)
        assert perf.mfu_from_time(1e12, 0.0, 1, 2.0) == 0.0


# ---------------------------------------------------------------------
# perf regression gate
# ---------------------------------------------------------------------

class TestPerfGate:

    def test_gate_passes_on_committed_baseline(self):
        from benchmark.perf_gate import flatten_metrics, gate
        report = perf.report_from_trace(_load_fixture())
        verdict = gate(flatten_metrics(report.to_dict()),
                       baseline_path=BASELINE)
        assert verdict["pass"], verdict
        assert verdict["n_checked"] >= 8
        assert verdict["n_failed"] == 0

    def test_gate_fails_loudly_on_2x_regression(self):
        from benchmark.perf_gate import check, flatten_metrics
        trace = copy.deepcopy(_load_fixture())
        for e in trace["traceEvents"]:
            if e.get("ph") in ("B", "E"):
                e["ts"] = e["ts"] * 2.0      # inject 2x latency
        report = perf.report_from_trace(trace)
        with open(BASELINE, encoding="utf-8") as f:
            baseline = json.load(f)
        verdict = check(flatten_metrics(report.to_dict()), baseline)
        assert not verdict["pass"]
        failed = {c["metric"]: c for c in verdict["checks"]
                  if not c["ok"]}
        assert "critical_path_us" in failed
        assert failed["critical_path_us"]["ratio"] == pytest.approx(
            2.0, rel=1e-3)
        assert "max_ratio" in failed["critical_path_us"]["reason"]

    def test_gate_cli_exit_codes(self, tmp_path):
        from benchmark import perf_gate
        assert perf_gate.main(["--trace", FIXTURE,
                               "--baseline", BASELINE]) == 0
        trace = copy.deepcopy(_load_fixture())
        for e in trace["traceEvents"]:
            if e.get("ph") in ("B", "E"):
                e["ts"] = e["ts"] * 2.0
        bad = tmp_path / "regressed.json"
        bad.write_text(json.dumps(trace))
        assert perf_gate.main(["--trace", str(bad),
                               "--baseline", BASELINE]) == 1

    def test_gate_verdicts_hit_metrics_registry(self):
        from benchmark.perf_gate import gate, flatten_metrics
        report = perf.report_from_trace(_load_fixture())
        gate(flatten_metrics(report.to_dict()), baseline_path=BASELINE)
        text = tmetrics.get_registry().to_prometheus_text()
        assert 'alpa_perf_gate_total{result="pass"}' in text

    def test_only_shared_metrics_checked(self):
        from benchmark.perf_gate import check
        verdict = check({"unknown_metric": 1.0},
                        {"metrics": {"other": {"value": 1.0,
                                               "max_ratio": 1.1}}})
        assert not verdict["pass"]          # nothing checked != pass
        assert verdict["n_checked"] == 0
        assert verdict["n_skipped"] == 1


# ---------------------------------------------------------------------
# end-to-end: live traced overlap step -> graph-joined report
# ---------------------------------------------------------------------

class TestLivePipeshard:

    def test_overlap_step_perf_report_and_debug_dump(
            self, fresh_trace, tmp_path):
        from alpa_tpu import PipeshardParallel
        from alpa_tpu.pipeline_parallel.layer_construction import (
            AutoLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            UniformStageOption)
        from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                      get_mlp_train_step)
        alpa_tpu.init("local")
        prev_mode = global_config.pipeline_dispatch_mode
        prev_peak = global_config.device_peak_tflops
        global_config.pipeline_dispatch_mode = "overlap"
        global_config.device_peak_tflops = 1.0   # CPU run: pin the peak
        try:
            method = PipeshardParallel(
                num_micro_batches=2,
                layer_option=AutoLayerOption(layer_num=4),
                stage_option=UniformStageOption(num_stages=4))
            step = get_mlp_train_step(method, use_value_and_grad=False)
            state, batch = create_mlp_train_state_and_batch(
                batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
                num_layers=4, manual_pipeline_layer=False)
            for _ in range(2):
                state, val = step(state, batch)
            float(val)
            ex = step.get_last_executable()
            assert ex.last_dispatch_stats["mode"] == "overlap"

            report = ex.get_perf_report()
            assert report is not None
            assert report.source == "trace"
            # spans joined 1:1 against the lowered program's op_meta,
            # so the walk rides real dataflow edges
            assert report.aligned, report.notes
            prog = ex._register_programs["overlap"]
            assert report.n_ops == len(prog.ops)
            assert report.envelope_us > 0
            # the walk spans the op window inside the step envelope
            # (the envelope also holds driver arg-placement / output
            # work, so coverage is < 1 on a live run; the exact
            # within-5% bound is pinned on the fixture above)
            cp = report.critical_path
            assert 0.0 < cp.total_us + cp.gap_us <= cp.envelope_us
            assert cp.coverage > 0.5
            # >= 2 mesh tracks, each with fractions summing to 1
            assert len(report.bubbles) >= 2
            for b in report.bubbles.values():
                assert sum(b.fractions().values()) == pytest.approx(
                    1.0, abs=1e-6)
                assert b.sched_num_clock is not None
            # S2: the pool recorded queue-wait/wire child spans
            pool_names = {s["name"] for s in fresh_trace.spans()
                          if (s["track"] or "").startswith(
                              "alpa-overlap")}
            assert "reshard.wait" in pool_names
            assert "reshard.wire" in pool_names
            assert report.transfers.pool_busy_us > 0
            # MFU attribution found the stage RUN spans
            assert report.stages, "no stage MFU rows"
            for s in report.stages.values():
                assert s.n_runs >= 1 and s.flops_per_run > 0
                assert s.mfu >= 0

            # what-if on the real DAG is monotone
            w = report.whatif("reshard")
            assert w["whatif_us"] <= w["baseline_us"]

            # gauges flowed into the central registry
            text = tmetrics.get_registry().to_prometheus_text()
            assert "alpa_critical_path_us" in text
            assert 'alpa_step_bubble_fraction{mesh="0"}' in text
            assert "alpa_stage_mfu{stage=" in text

            # perf_report.txt lands in the debug dump
            from alpa_tpu import monitoring
            dump = tmp_path / "dump"
            monitoring.dump_debug_info(ex, str(dump))
            txt = (dump / "perf_report.txt").read_text()
            assert "critical path" in txt
            assert "per-mesh bubbles" in txt
        finally:
            global_config.pipeline_dispatch_mode = prev_mode
            global_config.device_peak_tflops = prev_peak

    def test_flight_fallback_when_tracing_off(self):
        """Tracing off, flight ring on: get_perf_report still joins a
        step from the ring."""
        from alpa_tpu import PipeshardParallel
        from alpa_tpu.pipeline_parallel.layer_construction import (
            AutoLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            UniformStageOption)
        from alpa_tpu.telemetry import flight as tflight
        from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                      get_mlp_train_step)
        if not tflight.enabled():
            pytest.skip("flight recorder disabled")
        alpa_tpu.init("local")
        prev_mode = global_config.pipeline_dispatch_mode
        global_config.pipeline_dispatch_mode = "registers"
        # fresh empty recorder (tracing stays OFF): earlier tests may
        # have left a stale step span that would shadow the fallback
        old_rec = ttrace.set_recorder(TraceRecorder())
        try:
            method = PipeshardParallel(
                num_micro_batches=2,
                layer_option=AutoLayerOption(layer_num=4),
                stage_option=UniformStageOption(num_stages=4))
            step = get_mlp_train_step(method, use_value_and_grad=False)
            state, batch = create_mlp_train_state_and_batch(
                batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
                num_layers=4, manual_pipeline_layer=False)
            state, val = step(state, batch)
            float(val)
            ex = step.get_last_executable()
            assert not ttrace.enabled()
            report = ex.get_perf_report()
            assert report is not None
            assert report.source == "flight"
            assert report.n_ops > 0
            assert report.envelope_us > 0
        finally:
            global_config.pipeline_dispatch_mode = prev_mode
            ttrace.set_recorder(old_rec)
