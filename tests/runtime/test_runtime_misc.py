"""Runtime misc: memory-leak check, seed reproducibility
(ref tests/runtime/test_memory_leak.py + random-seed tests)."""
import gc

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu import DataParallel, ShardParallel
from alpa_tpu.testing import (assert_allclose, create_mlp_train_state_and_batch,
                              get_mlp_train_step)


class TestMemoryLeak:

    def test_no_buffer_growth_across_steps(self):
        """Steady-state training must not accumulate live device buffers
        (ref test_memory_leak.py)."""
        state, batch = create_mlp_train_state_and_batch()
        step = get_mlp_train_step(DataParallel(), use_value_and_grad=True)
        for _ in range(3):
            state, loss = step(state, batch)
        gc.collect()
        n0 = len(jax.live_arrays())
        for _ in range(10):
            state, loss = step(state, batch)
        float(loss)
        gc.collect()
        n1 = len(jax.live_arrays())
        assert n1 <= n0 + 4, f"live arrays grew {n0} -> {n1}"

    def test_executable_cache_bounded(self):
        """Same shapes -> one cached executable, not one per call."""
        state, batch = create_mlp_train_state_and_batch()
        step = get_mlp_train_step(ShardParallel(), use_value_and_grad=True)
        for _ in range(4):
            state, _ = step(state, batch)
        assert len(step._executable_cache) == 1


class TestSeedReproducibility:

    def test_same_seed_same_init(self):
        alpa_tpu.set_seed(123)
        s1, _ = create_mlp_train_state_and_batch()
        alpa_tpu.set_seed(123)
        s2, _ = create_mlp_train_state_and_batch()
        assert_allclose(jax.device_get(s1.params), jax.device_get(s2.params))

    def test_training_deterministic(self):
        outs = []
        for _ in range(2):
            state, batch = create_mlp_train_state_and_batch()
            step = get_mlp_train_step(DataParallel(),
                                      use_value_and_grad=True)
            for _ in range(3):
                state, loss = step(state, batch)
            outs.append(float(loss))
        assert outs[0] == outs[1]


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
