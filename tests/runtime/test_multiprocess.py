"""Multi-process runtime proof (VERDICT r1 next#6).

Spawns two real OS processes joined via ``jax.distributed`` (4 virtual
CPU devices each -> 8 global) and drives both ShardParallel and a
2-stage pipeshard train step whose stage meshes live on DIFFERENT
processes, with a serial-equivalence oracle inside each worker.  Analog
of the reference's Ray-emulated multi-host tests
(ref tests/pipeline_parallel/, alpa/device_mesh.py:979-1147).
"""
import os
import socket
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
WORKER = os.path.join(REPO_ROOT, "scripts", "multiprocess_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_workers(nproc, mode=None, timeout=540):
    port = _free_port()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
        "PYTHONPATH": REPO_ROOT,
    })
    args = [str(port)] + ([mode] if mode else [])
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(nproc)] + args,
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True) for i in range(nproc)
    ]
    outs = []
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"worker {i} timed out")
        outs.append((p.returncode, out, err))
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, (f"worker {i} rc={rc}\n--- stdout:\n{out[-2000:]}"
                         f"\n--- stderr:\n{err[-3000:]}")
        assert f"MP_OK {i}" in out, out[-2000:]
    return outs


from alpa_tpu.testing import skip_if_old_jax  # noqa: E402

_MULTIPROC_REASON = ("multi-controller jit over disjoint per-process mesh "
                     "slices fails inside the worker processes (device_put "
                     "to non-addressable shardings)")


@skip_if_old_jax(_MULTIPROC_REASON)
def test_two_process_runtime():
    outs = _run_workers(2)
    for _, out, _ in outs:
        assert "shard_parallel ok" in out
        assert "pipeshard ok" in out


@skip_if_old_jax(_MULTIPROC_REASON)
def test_four_process_auto_stage_runtime():
    """4 processes x 2 devices: AUTO stage construction, planned
    (packed-tile) cross-process resharding, and a measured per-instruction
    dispatch latency (VERDICT r2 next#5; SURVEY §7 hard part 5)."""
    import json

    outs = _run_workers(4, mode="auto", timeout=600)
    stats, stats4 = None, None
    for _, out, _ in outs:
        assert "auto pipeshard ok" in out
        assert "uniform4 ok" in out
        for line in out.splitlines():
            if line.startswith("dispatch_stats "):
                stats = json.loads(line[len("dispatch_stats "):])
            elif line.startswith("dispatch_stats4 "):
                stats4 = json.loads(line[len("dispatch_stats4 "):])
    assert stats is not None and stats4 is not None
    assert stats["n_instructions"] > 0
    # the driver loop must not dominate the step: per-instruction Python
    # overhead stays under 50 ms even on a loaded CI box (observed ~9 ms
    # on CPU, where RUN blocks on compute; async backends only enqueue)
    assert stats["per_inst_us"] < 50_000, stats
    # the one-stage-per-process leg actually crossed process boundaries
    # with the packed-tile plan
    assert stats4["by_opcode"]["RESHARD"]["n"] > 0
    assert stats4["executed_cross_mesh_bytes"] > 0


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
