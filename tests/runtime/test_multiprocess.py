"""Multi-process runtime proof (VERDICT r1 next#6).

Spawns two real OS processes joined via ``jax.distributed`` (4 virtual
CPU devices each -> 8 global) and drives both ShardParallel and a
2-stage pipeshard train step whose stage meshes live on DIFFERENT
processes, with a serial-equivalence oracle inside each worker.  Analog
of the reference's Ray-emulated multi-host tests
(ref tests/pipeline_parallel/, alpa/device_mesh.py:979-1147).
"""
import os
import socket
import subprocess
import sys

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
WORKER = os.path.join(REPO_ROOT, "scripts", "multiprocess_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_runtime():
    port = _free_port()
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "",
        "PYTHONPATH": REPO_ROOT,
    })
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), "2", str(port)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True) for i in range(2)
    ]
    outs = []
    for i, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=540)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail(f"worker {i} timed out")
        outs.append((p.returncode, out, err))
    for i, (rc, out, err) in enumerate(outs):
        assert rc == 0, (f"worker {i} rc={rc}\n--- stdout:\n{out[-2000:]}"
                         f"\n--- stderr:\n{err[-3000:]}")
        assert f"MP_OK {i}" in out, out[-2000:]
        assert "shard_parallel ok" in out
        assert "pipeshard ok" in out


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
