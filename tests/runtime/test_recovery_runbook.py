"""The automated chip-recovery path must not rot: every script the
runbook (and the watch loop that fires it) invokes exists, parses, and
the python ones compile.  A rename breaking this chain would silently
cost an entire round's bench window (the relay wedge playbook depends
on unattended recovery)."""
import os
import py_compile
import re
import subprocess

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _referenced_scripts(sh_path):
    with open(sh_path, encoding="utf-8") as f:
        text = f.read()
    return sorted(set(re.findall(r"(scripts/[\w./]+\.(?:py|sh))", text)))


def test_runbook_and_watch_reference_existing_scripts():
    for sh in ("scripts/chip_recovery_runbook.sh",
               "scripts/chip_watch.sh"):
        path = os.path.join(REPO, sh)
        assert os.path.exists(path), sh
        # shell parses
        subprocess.run(["bash", "-n", path], check=True)
        for ref in _referenced_scripts(path):
            full = os.path.join(REPO, ref)
            assert os.path.exists(full), f"{sh} references missing {ref}"
            if ref.endswith(".py"):
                py_compile.compile(full, doraise=True)
            else:
                subprocess.run(["bash", "-n", full], check=True)


def test_bench_probe_flag_exists():
    with open(os.path.join(REPO, "bench.py"), encoding="utf-8") as f:
        src = f.read()
    assert '"--probe"' in src  # the watch loop's probe contract
