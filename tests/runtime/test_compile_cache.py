"""Persistent compile cache (ISSUE 2): unit behavior of the two-tier
store plus end-to-end replay through the pipeshard compile path.

The end-to-end oracle is twofold: (1) hit counters — a second compile in
the same process hits the memory tier, and a simulated restart (fresh
CompileCache object over the same directory) hits the disk tier with
zero new ILP solves; (2) determinism — a plan replayed from the cache
produces an executable whose plan fingerprint (instruction stream +
per-stage shardings) is identical to the fresh solve's.
"""
import os

import pytest

import alpa_tpu
from alpa_tpu import PipeshardParallel
from alpa_tpu.compile_cache import (CompileCache, fingerprint_parts,
                                    get_compile_cache, reset_compile_cache)
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import AutoStageOption
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)


class TestCompileCacheUnit:

    def test_fingerprint_stable_and_discriminating(self):
        assert fingerprint_parts(["a", "b"]) == fingerprint_parts(["a", "b"])
        assert fingerprint_parts(["a", "b"]) != fingerprint_parts(["ab"])
        assert fingerprint_parts(["a"]) != fingerprint_parts(["b"])

    def test_fingerprint_masks_addresses(self):
        # str(jaxpr) embeds live function addresses; the same program must
        # fingerprint identically across traces
        a = "jvp_jaxpr_thunk=<function memoized at 0x7fb0765e3d90>"
        b = "jvp_jaxpr_thunk=<function memoized at 0x7fb0765257e0>"
        assert fingerprint_parts([a]) == fingerprint_parts([b])

    def test_memory_tier_lru(self):
        cache = CompileCache(cache_dir=None, memory_entries=2)
        for i in range(3):
            cache.put("ilp", f"ilp-k{i}", i)
        assert cache.get("ilp", "ilp-k0") is None  # evicted
        assert cache.get("ilp", "ilp-k2") == 2
        s = cache.stats()["namespaces"]["ilp"]
        assert s["puts"] == 3 and s["hits"] == 1 and s["misses"] == 1

    def test_disk_tier_roundtrip_and_promotion(self, tmp_path):
        d = str(tmp_path)
        CompileCache(cache_dir=d).put("ilp", "ilp-key", {"x": 1})
        # fresh object = simulated restart; first get is a disk hit
        cache2 = CompileCache(cache_dir=d)
        assert cache2.get("ilp", "ilp-key") == {"x": 1}
        s = cache2.stats()["namespaces"]["ilp"]
        assert s["disk_hits"] == 1
        # promoted: second get hits memory (disk_hits stays 1)
        assert cache2.get("ilp", "ilp-key") == {"x": 1}
        assert cache2.stats()["namespaces"]["ilp"]["disk_hits"] == 1

    def test_corrupt_disk_entry_is_a_miss(self, tmp_path):
        d = str(tmp_path)
        cache = CompileCache(cache_dir=d)
        cache.put("ilp", "ilp-bad", {"x": 1})
        path = os.path.join(d, "ilp-bad.pkl")
        with open(path, "wb") as f:
            f.write(b"not a pickle")
        fresh = CompileCache(cache_dir=d)
        assert fresh.get("ilp", "ilp-bad") is None
        assert not os.path.exists(path)  # dropped, not retried forever

    def test_clear_by_namespace(self, tmp_path):
        cache = CompileCache(cache_dir=str(tmp_path))
        cache.put("ilp", "ilp-a", 1)
        cache.put("stage_dp", "stage_dp-b", 2)
        assert cache.clear(namespace="ilp") == 1
        assert cache.get("ilp", "ilp-a") is None
        assert cache.get("stage_dp", "stage_dp-b") == 2


def _compile_pipeshard():
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=4),
        stage_option=AutoStageOption())
    step = get_mlp_train_step(method, use_value_and_grad=False)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)
    step(state, batch)
    return step.get_last_executable()


class TestCompileCacheEndToEnd:

    def test_warm_compile_hits_and_replays_deterministically(self, tmp_path):
        from alpa_tpu.api import clear_executable_cache
        global_config.compile_cache_dir = str(tmp_path)
        reset_compile_cache()
        alpa_tpu.init("local")

        ex1 = _compile_pipeshard()
        s1 = get_compile_cache().stats()["namespaces"]
        assert s1["ilp"]["misses"] > 0 and s1["ilp"]["hits"] == 0
        assert s1["ilp"]["puts"] == s1["ilp"]["misses"]
        assert s1["stage_dp"]["puts"] == 1
        assert s1["parallel_plan"]["puts"] == 1

        # second compile in the same process: every solve replays
        clear_executable_cache()
        ex2 = _compile_pipeshard()
        s2 = get_compile_cache().stats()["namespaces"]
        assert s2["ilp"]["hits"] == s1["ilp"]["misses"]
        assert s2["ilp"]["misses"] == s1["ilp"]["misses"]  # no new solves
        assert s2["stage_dp"]["hits"] == 1
        assert ex2.get_plan_fingerprint() == ex1.get_plan_fingerprint()

        # simulated restart: fresh cache object over the same directory —
        # all hits must come from disk, zero ILP/stage-DP solves
        clear_executable_cache()
        reset_compile_cache(CompileCache(cache_dir=str(tmp_path)))
        ex3 = _compile_pipeshard()
        s3 = get_compile_cache().stats()["namespaces"]
        assert s3["ilp"]["misses"] == 0, "restart re-ran the ILP"
        assert s3["ilp"]["disk_hits"] > 0
        assert s3["stage_dp"]["misses"] == 0
        assert s3["stage_dp"]["disk_hits"] == 1
        assert ex3.get_plan_fingerprint() == ex1.get_plan_fingerprint()

    def test_cache_disabled_never_stores(self):
        alpa_tpu.init("local")
        prev = global_config.compile_cache_enabled
        global_config.compile_cache_enabled = False
        try:
            _compile_pipeshard()
            assert get_compile_cache().stats()["namespaces"] == {}
        finally:
            global_config.compile_cache_enabled = prev

    def test_monitoring_report(self, tmp_path):
        from alpa_tpu.monitoring import (format_compile_cache_report,
                                         get_compile_cache_stats)
        global_config.compile_cache_dir = str(tmp_path)
        reset_compile_cache()
        alpa_tpu.init("local")
        _compile_pipeshard()
        stats = get_compile_cache_stats()
        assert set(stats["namespaces"]) >= {"ilp", "stage_dp",
                                            "parallel_plan"}
        report = format_compile_cache_report()
        assert "ilp" in report and str(tmp_path) in report
