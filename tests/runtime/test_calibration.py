"""Profile-guided replanning (ISSUE 12): measured-cost calibration
store, model-drift observability, and hot-swapped replans.

Oracle 1 (store): robust stats and drift math, disk persistence with
the content-addressed per-entry layout, and a fingerprint that is
invariant to sample counts but sensitive to measured values.
Oracle 2 (parity, satellite 3): with tracing off, the flight-ring
fallback calibrates the *same signatures with the same sample counts*
as the traced path on the committed fixture trace.
Oracle 3 (off-mode): ``replan_mode=off`` consults nothing — strategy
choices, costs, and compile-cache keys are byte-identical to a build
with no store, even with a populated (mispriced) store on disk.
Oracle 4 (replan): a deliberately mispriced edge flips the strategy
choice, the re-simulated critical path never exceeds the original's,
and a warm restart with an unchanged store replays from cache with an
identical fingerprint (the committed ``replan.*`` perf-gate baselines
pin the full bench replay).
Oracle 5 (observability): the drift gauges flow to ``/metrics``,
``calibration.txt`` lands in the debug dump, the ``drift`` / ``--edges``
CLIs render the fixture, and the profiling DB stamps its schema and
warns on out-of-range lookups.
Oracle 6 (live): ``consider_replan`` on a real 2-mesh pipeshard
executable — None when off, a suggest verdict that applies nothing,
and an auto hot-swap that re-lowers (verifier re-run) while the step
output stays bit-exact.
"""
import json
import logging
import os

import numpy as np
import pytest

import alpa_tpu
from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry import calibration as cal
from alpa_tpu.telemetry import metrics as tmetrics
from alpa_tpu.telemetry import perf

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
FIXTURE = os.path.join(REPO, "benchmark", "results",
                       "perf_gate_fixture_trace.json")
BASELINE = os.path.join(REPO, "benchmark", "results",
                        "perf_gate_baseline.json")


def _load_fixture():
    with open(FIXTURE, encoding="utf-8") as f:
        return json.load(f)


@pytest.fixture(autouse=True)
def _calibration_env():
    """Fresh global store + restored replan/wire knobs per test."""
    prev = (global_config.replan_mode,
            global_config.calibration_min_samples,
            global_config.calibration_dir,
            global_config.reshard_strategy,
            global_config.resharding_wire_model,
            global_config.resharding_transfer_latency_s,
            global_config.resharding_wire_bandwidth,
            global_config.pipeline_dispatch_mode)
    cal.reset_calibration_store(None)
    yield
    (global_config.replan_mode,
     global_config.calibration_min_samples,
     global_config.calibration_dir,
     global_config.reshard_strategy,
     global_config.resharding_wire_model,
     global_config.resharding_transfer_latency_s,
     global_config.resharding_wire_bandwidth,
     global_config.pipeline_dispatch_mode) = prev
    cal.reset_calibration_store(None)


# ---------------------------------------------------------------------
# Oracle 1: the store itself
# ---------------------------------------------------------------------

class TestStore:

    def test_robust_stats_and_drift(self):
        store = cal.CalibrationStore(None)
        for v in (10.0, 2.0, 7.0, 7.0, 100.0):
            store.observe("reshard_wire", "edge:a->b", v, modeled_us=2.0)
        e = store.get("reshard_wire", "edge:a->b")
        assert e.count == 5
        assert e.median_us == pytest.approx(7.0)
        assert e.p90_us <= 100.0
        assert e.drift_ratio == pytest.approx(3.5)
        assert e.ewma_us > 0

    def test_disk_persistence_and_reload(self, tmp_path):
        d = str(tmp_path / "cal")
        store = cal.CalibrationStore(d)
        store.observe("reshard_wire", "edge:a->b", 7.0, modeled_us=2.0)
        store.observe("stage_run", "stage:s0", 100.0)
        files = sorted(os.listdir(d))
        assert len(files) == 2
        assert any(f.startswith("reshard_wire-") for f in files)
        assert any(f.startswith("stage_run-") for f in files)
        # every entry file is valid stamped JSON
        for f in files:
            with open(os.path.join(d, f), encoding="utf-8") as fh:
                data = json.load(fh)
            assert data["format"] == cal.CALIBRATION_FORMAT_VERSION
        reloaded = cal.CalibrationStore(d)
        assert len(reloaded) == 2
        assert reloaded.get("reshard_wire",
                            "edge:a->b").median_us == pytest.approx(7.0)
        assert reloaded.fingerprint() == store.fingerprint()

    def test_wrong_format_entry_skipped(self, tmp_path):
        d = str(tmp_path / "cal")
        store = cal.CalibrationStore(d)
        store.observe("stage_run", "stage:s0", 100.0)
        bogus = os.path.join(d, "stage_run-deadbeefdeadbeef.json")
        with open(bogus, "w", encoding="utf-8") as f:
            json.dump({"format": 999, "samples": "nope"}, f)
        reloaded = cal.CalibrationStore(d)       # must not raise
        assert len(reloaded) == 1

    def test_fingerprint_count_invariant_value_sensitive(self):
        store = cal.CalibrationStore(None)
        store.observe("stage_run", "stage:s0", 100.0)
        fp0 = store.fingerprint()
        store.observe("stage_run", "stage:s0", 100.0)   # same value
        assert store.fingerprint() == fp0
        store.observe("stage_run", "stage:s0", 999.0)   # moves the stats
        assert store.fingerprint() != fp0

    def test_min_samples_gates_consult(self):
        global_config.calibration_min_samples = 3
        store = cal.CalibrationStore(None)
        store.observe("stage_run", "stage:s0", 100.0)
        store.observe("stage_run", "stage:s0", 100.0)
        assert store.measured_us("stage_run", "stage:s0") is None
        store.observe("stage_run", "stage:s0", 100.0)
        assert store.measured_us("stage_run",
                                 "stage:s0") == pytest.approx(100.0)

    def test_cache_token_off_vs_active(self):
        global_config.replan_mode = "off"
        assert cal.calibration_cache_token() is None
        global_config.replan_mode = "suggest"
        tok = cal.calibration_cache_token()
        assert tok is not None and tok.startswith("cal:")
        # stage-DP / ILP key parts ride the same token
        from alpa_tpu.pipeline_parallel.stage_construction import (
            _cal_key_parts)
        assert _cal_key_parts() == [tok]
        global_config.replan_mode = "off"
        assert _cal_key_parts() == []


# ---------------------------------------------------------------------
# Oracle 2: traced vs flight-ring ingest parity on the fixture
# ---------------------------------------------------------------------

class TestIngestParity:

    PINNED_COUNTS = {"stage:stage_0": 4, "stage:stage_1": 4,
                     "edge:stage_0->stage_1": 4}

    def test_traced_ingest_pinned(self):
        store = cal.CalibrationStore(None)
        ingested = cal.ingest_chrome_trace(_load_fixture(), store=store)
        assert ingested == self.PINNED_COUNTS
        assert store.get("stage_run",
                         "stage:stage_0").median_us == pytest.approx(100.0)
        assert store.get("stage_run",
                         "stage:stage_1").median_us == pytest.approx(120.0)
        # pool reshard.wire children: the true wire time, 7 us
        assert store.get(
            "reshard_wire",
            "edge:stage_0->stage_1").median_us == pytest.approx(7.0)

    def test_flight_fallback_same_keys_and_counts(self):
        """Satellite 3: no tracing (no pool spans) still produces store
        entries — same signatures, same sample counts; the wire value is
        the coarser LAUNCH->WAIT envelope."""
        traced = cal.CalibrationStore(None)
        cal.ingest_chrome_trace(_load_fixture(), store=traced)

        report = perf.report_from_trace(_load_fixture())
        flight = cal.CalibrationStore(None)
        ingested = cal.ingest_report(report, store=flight)

        assert ingested == self.PINNED_COUNTS
        assert ({(e.kind, e.signature, e.count) for e in flight.entries()}
                == {(e.kind, e.signature, e.count)
                    for e in traced.entries()})
        # stage medians identical; wire differs (envelope vs wire leg)
        for sig in ("stage:stage_0", "stage:stage_1"):
            assert flight.get("stage_run", sig).median_us == \
                traced.get("stage_run", sig).median_us
        assert flight.get(
            "reshard_wire",
            "edge:stage_0->stage_1").median_us == pytest.approx(35.5)


# ---------------------------------------------------------------------
# Oracle 3: off-mode is byte-identical
# ---------------------------------------------------------------------

def _two_mesh_edge():
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()
    src_mesh = Mesh(np.array(devs[:4]), ("x",))
    dst_mesh = Mesh(np.array(devs[4:8]), ("x",))
    return (NamedSharding(src_mesh, P("x", None)),
            NamedSharding(dst_mesh, P()))


class TestOffMode:

    def _misprice_winner(self, store, src, dst):
        from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr
        global_config.replan_mode = "suggest"
        chosen, costs, _ = cmr.choose_strategy((8, 8), 4, src, dst)
        sig = cal.wire_signature((8, 8), 4, cmr._sharding_key(src),
                                 cmr._sharding_key(dst), chosen)
        for _ in range(4):
            store.observe("reshard_wire", sig, 500.0,
                          modeled_us=costs[chosen] * 1e6)
        global_config.replan_mode = "off"
        return chosen, costs

    def test_off_mode_choice_identical_with_populated_store(self):
        from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr
        global_config.resharding_wire_model = "link"
        global_config.resharding_transfer_latency_s = 1e-5
        src, dst = _two_mesh_edge()
        global_config.replan_mode = "off"
        base_chosen, base_costs, _ = cmr.choose_strategy((8, 8), 4,
                                                         src, dst)
        store = cal.CalibrationStore(None)
        cal.reset_calibration_store(store)
        analytic_chosen, _ = self._misprice_winner(store, src, dst)
        assert analytic_chosen == base_chosen

        chosen, costs, _ = cmr.choose_strategy((8, 8), 4, src, dst)
        assert chosen == base_chosen
        assert costs == base_costs                 # byte-identical
        global_config.replan_mode = "suggest"
        flipped, _, _ = cmr.choose_strategy((8, 8), 4, src, dst)
        assert flipped != base_chosen              # the store now binds

    def test_off_mode_cache_key_unchanged(self):
        """A decision cached before the store existed replays under
        off-mode with a populated store: the key has no calibration
        part."""
        from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr
        global_config.resharding_wire_model = "link"
        global_config.resharding_transfer_latency_s = 1e-5
        src, dst = _two_mesh_edge()
        global_config.replan_mode = "off"
        chosen0, _, from_cache0 = cmr.resolve_strategy((8, 8), 4,
                                                       src, dst)
        assert not from_cache0
        store = cal.CalibrationStore(None)
        cal.reset_calibration_store(store)
        self._misprice_winner(store, src, dst)
        chosen1, _, from_cache1 = cmr.resolve_strategy((8, 8), 4,
                                                       src, dst)
        assert from_cache1 and chosen1 == chosen0
        # under suggest the key gains the fingerprint -> fresh solve,
        # flipped decision; resolving again replays it from cache
        global_config.replan_mode = "suggest"
        chosen2, _, from_cache2 = cmr.resolve_strategy((8, 8), 4,
                                                       src, dst)
        assert not from_cache2 and chosen2 != chosen0
        chosen3, _, from_cache3 = cmr.resolve_strategy((8, 8), 4,
                                                       src, dst)
        assert from_cache3 and chosen3 == chosen2

    def test_estimate_stage_cost_consults_only_when_active(self):
        from alpa_tpu import mesh_profiling as mp
        from alpa_tpu.device_mesh import LogicalDeviceMesh
        store = cal.CalibrationStore(None)
        cal.reset_calibration_store(store)
        global_config.replan_mode = "off"

        class _Comp:                               # zero-FLOP stage
            eqns = ()

        mesh = LogicalDeviceMesh(None, np.arange(2).reshape(1, 2))
        analytic = mp.estimate_stage_cost([_Comp()], mesh, None,
                                          use_ilp=False)
        assert len(store) == 0                     # off: not consulted
        global_config.replan_mode = "suggest"
        same = mp.estimate_stage_cost([_Comp()], mesh, None,
                                      use_ilp=False)
        assert same == pytest.approx(analytic)     # no samples yet
        sig = cal.stage_cost_signature(0.0, 2)
        for _ in range(3):
            store.observe("stage_run", sig, 12345.0)
        assert mp.estimate_stage_cost(
            [_Comp()], mesh, None,
            use_ilp=False) == pytest.approx(12345e-6)
        # the consult attached the analytic prediction it superseded
        e = store.get("stage_run", sig)
        assert e.modeled_us == pytest.approx(analytic * 1e6)


# ---------------------------------------------------------------------
# Oracle 4: the mispriced-edge replan replay (bench + committed gate)
# ---------------------------------------------------------------------

class TestReplanReplay:

    def test_bench_replay_meets_committed_gate(self):
        from benchmark import replan_bench
        from benchmark.perf_gate import check
        res = replan_bench.run()
        gm = res["gate_metrics"]
        # acceptance: replanning a mispriced edge never worsens the
        # simulated critical path
        assert gm["replan.critical_path_ratio"] <= 1.0
        assert gm["replan.strategy_flipped"] == 1.0
        # warm restart: unchanged store -> identical fingerprint and a
        # cache replay instead of a fresh solve
        assert gm["replan.fingerprint_stable"] == 1.0
        assert gm["replan.warm_resolve_cached"] == 1.0
        # injected misprice surfaces as drift (measured/modeled = 50)
        assert gm["replan.drift_ratio_worst"] == pytest.approx(50.0)
        with open(BASELINE, encoding="utf-8") as f:
            verdict = check(gm, json.load(f))
        assert verdict["pass"], verdict
        assert verdict["n_checked"] >= 6


# ---------------------------------------------------------------------
# Oracle 5: drift observability + prof-DB validation
# ---------------------------------------------------------------------

class TestObservability:

    def test_drift_gauges_and_report_text(self):
        store = cal.get_calibration_store()
        cal.ingest_chrome_trace(_load_fixture(), store=store)
        store.set_modeled("reshard_wire", "edge:stage_0->stage_1", 2.0)
        text = tmetrics.get_registry().to_prometheus_text()
        assert 'alpa_cost_model_drift_ratio{kind="reshard_wire"} 3.5' \
            in text
        assert 'alpa_calibration_samples_total{kind="stage_run"} 8' \
            in text
        report = cal.format_calibration_report(store)
        assert "calibration store: 3 entries" in report
        assert "edge:stage_0->stage_1" in report
        assert "3.50" in report                    # the drift column

    def test_drift_cli_and_edges_cli(self, capsys):
        from scripts import perf_tool, trace_tool
        perf_tool.main(["drift", FIXTURE, "--json"])
        rows = json.loads(capsys.readouterr().out)
        assert {r["signature"] for r in rows} == {
            "stage:stage_0", "stage:stage_1", "edge:stage_0->stage_1"}
        trace_tool.main(["summarize", FIXTURE, "--edges"])
        out = capsys.readouterr().out
        assert "reshard edges" in out
        assert "stage_0->stage_1" in out
        assert "direct_p2p" in out                 # untagged label
        assert "7.0" in out                        # wire median us

    def test_edge_wire_table_values(self):
        joined = perf._join_spans(
            perf.spans_from_chrome(_load_fixture()), None)
        rows = cal.edge_wire_table(joined)
        assert len(rows) == 1
        r = rows[0]
        assert (r["src"], r["dst"]) == ("stage_0", "stage_1")
        assert r["strategy"] == "direct_p2p"
        assert r["n"] == 4
        assert r["median_us"] == pytest.approx(7.0)
        assert r["bytes"] is None and r["gbps"] is None

    def test_prof_db_schema_stamp_roundtrip(self, tmp_path):
        from alpa_tpu import mesh_profiling as mp
        r = mp.MeshProfilingResult()
        r.record("all_reduce", ("1x2", 2), 1024.0, 1e-4)
        db = mp.ProfilingResultDatabase({"1x2-test": r})
        path = str(tmp_path / "db.json")
        db.save(path)
        with open(path, encoding="utf-8") as f:
            raw = json.load(f)
        assert raw["schema_version"] == mp.PROF_DB_SCHEMA_VERSION
        assert "1x2-test" in raw["meshes"]
        loaded = mp.ProfilingResultDatabase.load(path)
        assert loaded.query("1x2-test").estimate(
            "all_reduce", ("1x2", 2), 1024.0) == pytest.approx(1e-4)

    def test_prof_db_legacy_load_warns(self, tmp_path, caplog):
        from alpa_tpu import mesh_profiling as mp
        path = str(tmp_path / "legacy.json")
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"1x2-legacy": mp.MeshProfilingResult().to_json()},
                      f)
        with caplog.at_level(logging.WARNING,
                             logger="alpa_tpu.mesh_profiling"):
            db = mp.ProfilingResultDatabase.load(path)
        assert db.query("1x2-legacy") is not None
        assert any("no schema_version stamp" in r.message
                   for r in caplog.records)

    def test_committed_dbs_are_stamped(self):
        for name in ("prof_database_cpu8.json", "prof_database_tpu.json"):
            with open(os.path.join(REPO, name), encoding="utf-8") as f:
                raw = json.load(f)
            assert raw.get("schema_version") == 1, name

    def test_out_of_range_estimate_warns_once(self, caplog):
        from alpa_tpu import mesh_profiling as mp
        r = mp.MeshProfilingResult()
        key = ((0, 4), 4, "oob-test")
        r.record("all_gather", key, 100.0, 1e-5)
        r.record("all_gather", key, 1000.0, 1e-4)
        with caplog.at_level(logging.WARNING,
                             logger="alpa_tpu.mesh_profiling"):
            v = r.estimate("all_gather", key, 1e6)
            r.estimate("all_gather", key, 1e6)     # second: silent
        assert v == pytest.approx(1e-4)            # clamped, not wild
        warned = [rec for rec in caplog.records
                  if "out of measured range" in rec.message]
        assert len(warned) == 1
        assert "oob-test" in warned[0].message     # key (mesh shape) shown
        with caplog.at_level(logging.WARNING,
                             logger="alpa_tpu.mesh_profiling"):
            assert r.estimate("all_gather", key,
                              500.0) is not None   # in-range: silent
        assert len([rec for rec in caplog.records
                    if "out of measured range" in rec.message]) == 1


# ---------------------------------------------------------------------
# Oracle 6: consider_replan on a live 2-mesh pipeshard executable
# ---------------------------------------------------------------------

def _build_pipeshard_step():
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.pipeline_parallel.layer_construction import (
        ManualLayerOption)
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                  get_mlp_train_step)
    alpa_tpu.init("local")
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=ManualLayerOption(),
        stage_option=UniformStageOption(num_stages=2))
    state, batch = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    step = get_mlp_train_step(method, use_value_and_grad=True)
    return step, state, batch


class TestConsiderReplan:

    def test_live_off_suggest_auto(self, tmp_path):
        step, state, batch = _build_pipeshard_step()
        global_config.replan_mode = "off"
        state, loss0 = step(state, batch)
        loss0 = float(loss0)
        ex = step.get_last_executable()

        # off: no verdict, nothing consulted
        assert ex.consider_replan() is None

        # suggest: verdict without application; perf ingest fed the
        # store (per-stage RUN samples at minimum)
        global_config.replan_mode = "suggest"
        v = ex.consider_replan()
        assert v is not None
        assert v["mode"] == "suggest" and v["applied"] is False
        assert v["baseline_critical_path_us"] > 0
        assert v["predicted_critical_path_us"] > 0
        assert isinstance(v["strategy_flips"], list)
        assert v["calibration_fingerprint"]
        store = cal.get_calibration_store()
        assert any(e.kind == "stage_run" for e in store.entries())

        # calibration.txt lands in the debug dump
        from alpa_tpu import monitoring
        dump = tmp_path / "dump"
        monitoring.dump_debug_info(ex, str(dump))
        txt = (dump / "calibration.txt").read_text()
        assert "calibration store" in txt

        # auto: hot-swap path — the verdict reports both fingerprints
        # and a step replayed after the (possible) re-lowering is
        # bit-exact against the pre-replan program.  The train step
        # donates its state, so each run gets an identical fresh state.
        from alpa_tpu.testing import create_mlp_train_state_and_batch
        state_a, batch_a = create_mlp_train_state_and_batch(
            batch_size=64, num_layers=4, manual_pipeline_layer=True)
        _, loss_a = step(state_a, batch_a)
        loss_a = float(loss_a)
        global_config.replan_mode = "auto"
        v2 = ex.consider_replan()
        assert v2 is not None and v2["mode"] == "auto"
        assert "plan_fingerprint_before" in v2
        assert "plan_fingerprint_after" in v2
        assert v2["applied"] == bool(v2["strategy_flips"])
        if not v2["strategy_flips"]:
            assert v2["plan_fingerprint_before"] == \
                v2["plan_fingerprint_after"]
        state_b, batch_b = create_mlp_train_state_and_batch(
            batch_size=64, num_layers=4, manual_pipeline_layer=True)
        _, loss_b = step(state_b, batch_b)
        assert float(loss_b) == loss_a
