"""Register-file dispatch fast path (ISSUE 2 tentpole).

Oracle 1: numerics — the register path must be bit-identical to the
sequential interpreter over multiple donated train steps (same RUN
executables, same resharding endpoints, only the dispatch machinery
differs).  Oracle 2: structure — the lowering covers every instruction,
resolves every (var, microbatch) key to a slot, and the executable
reports mode "registers" with stable per-call stats.
"""
import numpy as np
import pytest

import alpa_tpu
import jax
from alpa_tpu import PipeshardParallel
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)


@pytest.fixture(autouse=True)
def _restore_dispatch_mode():
    prev = global_config.pipeline_dispatch_mode
    yield
    global_config.pipeline_dispatch_mode = prev


def _fresh_step_and_state(num_layers=4, num_stages=4):
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=num_layers),
        stage_option=UniformStageOption(num_stages=num_stages))
    step = get_mlp_train_step(method, use_value_and_grad=False)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=num_layers, manual_pipeline_layer=False)
    return step, state, batch


def _run_steps(mode, n_steps=3):
    global_config.pipeline_dispatch_mode = mode
    step, state, batch = _fresh_step_and_state()
    val = None
    for _ in range(n_steps):
        state, val = step(state, batch)
    ex = step.get_last_executable()
    return state, val, ex


def test_register_path_matches_interpreter_bitwise():
    alpa_tpu.init("local")
    state_s, val_s, ex_s = _run_steps("sequential")
    state_r, val_r, ex_r = _run_steps("registers")
    assert ex_s.last_dispatch_stats["mode"] == "sequential"
    assert ex_r.last_dispatch_stats["mode"] == "registers"
    leaves_s = jax.tree_util.tree_leaves(state_s.params)
    leaves_r = jax.tree_util.tree_leaves(state_r.params)
    assert len(leaves_s) == len(leaves_r) > 0
    for a, b in zip(leaves_s, leaves_r):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(val_s), np.asarray(val_r))


def test_auto_mode_picks_overlap_when_eligible():
    """auto on a multi-mesh payload with cross-mesh RESHARDs upgrades to
    overlap dispatch (ISSUE 4); with overlap_resharding off it pins the
    synchronous register replay."""
    alpa_tpu.init("local")
    _, _, ex = _run_steps("auto", n_steps=1)
    assert ex.last_dispatch_stats["mode"] == "overlap"
    prev = global_config.overlap_resharding
    global_config.overlap_resharding = False
    try:
        _, _, ex = _run_steps("auto", n_steps=1)
        assert ex.last_dispatch_stats["mode"] == "registers"
    finally:
        global_config.overlap_resharding = prev


def test_lowering_covers_every_instruction():
    alpa_tpu.init("local")
    _, _, ex = _run_steps("registers", n_steps=1)
    prog = ex._register_program
    assert prog is not None
    assert prog.n_instructions == len(ex.instructions)
    # one op per original instruction, minus ops saved by coalescing
    assert len(prog.ops) <= prog.n_instructions
    if prog.n_coalesced_groups == 0:
        assert len(prog.ops) == prog.n_instructions
    by = prog.by_opcode
    assert set(by) == {"RUN", "RESHARD", "FREE"}
    assert sum(by.values()) == prog.n_instructions
    assert prog.num_slots > 0
    # every op's fingerprint input is stable across calls
    assert prog.fingerprint() == prog.fingerprint()


def test_register_stats_shape():
    alpa_tpu.init("local")
    _, _, ex = _run_steps("registers", n_steps=2)
    st = ex.last_dispatch_stats
    assert st["mode"] == "registers"
    assert st["per_inst_us"] > 0
    assert st["n_instructions"] == len(ex.instructions)


def test_planned_resharding_falls_back_to_interpreter():
    """The register path requires device_put resharding; "planned" mode
    must fall back to the interpreter even when registers is requested."""
    alpa_tpu.init("local")
    prev = global_config.resharding_execution
    global_config.resharding_execution = "planned"
    try:
        _, _, ex = _run_steps("auto", n_steps=1)
        assert ex.last_dispatch_stats["mode"] != "registers"
    finally:
        global_config.resharding_execution = prev
