"""Mesh profiling: measurement, fitting, persistence, calibrated costs.

The measured-DB path is VERDICT r1 #2: cost-model decisions must trace to
measurements, not abstract units (ref mesh_profiling.py:392-725).
"""
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu.device_mesh import LogicalDeviceMesh, get_global_cluster
from alpa_tpu.mesh_profiling import (MeshProfilingResult,
                                     ProfilingResultDatabase,
                                     profile_one_mesh)


def _synthetic_result(sec_per_flop=1e-12, sec_per_byte=1e-9):
    res = MeshProfilingResult()
    for flops in (1e6, 1e9):
        res.record("dot", ("f32",), flops, flops * sec_per_flop)
    for kind in ("all_reduce", "all_gather", "reduce_scatter",
                 "all_to_all"):
        for nbytes in (1e3, 1e6):
            res.record(kind, ("f32", 8), nbytes,
                       1e-5 + nbytes * sec_per_byte)
    return res


class TestProfilingDatabase:

    def test_fit_recovers_alpha_beta(self):
        cal = _synthetic_result().fit()
        for kind in ("all_reduce", "all_gather"):
            alpha, beta = cal.alpha_beta(kind)
            assert alpha == pytest.approx(1e-5, rel=1e-3)
            assert beta == pytest.approx(1e-9, rel=1e-3)
        assert cal.sec_per_flop(1e9) == pytest.approx(1e-12, rel=1e-6)

    def test_json_roundtrip(self, tmp_path):
        db = ProfilingResultDatabase()
        db.update_one_mesh("1x8-cpu", _synthetic_result())
        path = str(tmp_path / "db.json")
        db.save(path)
        db2 = ProfilingResultDatabase.load(path)
        res = db2.query("1x8-cpu")
        assert res is not None
        assert res.estimate("dot", ("f32",), 1e9) == pytest.approx(1e-3)
        cal = db2.best_result().fit()
        assert cal.alpha_beta("all_to_all") is not None

    def test_calibrated_logical_mesh_costs_are_seconds(self):
        cal = _synthetic_result().fit()
        mesh = LogicalDeviceMesh(None, np.arange(8).reshape(1, 8),
                                 calibration=cal)
        assert mesh.calibrated
        # 1 MB all-reduce on 8 devices: alpha + beta * 2 * 7/8 * 1e6
        got = mesh.all_reduce_cost(1e6, 1)
        want = 1e-5 + 1e-9 * 2 * (7 / 8) * 1e6
        assert got == pytest.approx(want, rel=1e-3)
        # uncalibrated mesh keeps abstract units (tie-break constants)
        abstract = LogicalDeviceMesh(None, np.arange(8).reshape(1, 8))
        assert abstract.all_reduce_cost(1e6, 1) > 1.0

    def test_profile_one_mesh_measures(self):
        """Real measurement on the 8-device CPU mesh: dots + collectives
        recorded, fits positive."""
        alpa_tpu.init("local")
        mesh = get_global_cluster().get_physical_mesh()
        res = profile_one_mesh(mesh, sizes=(1 << 14, 1 << 16),
                               dot_ns=(256, 512))
        assert res.dot_cost_dict
        cal = res.fit()
        assert cal.sec_per_flop(2 * 512**3) > 0
        if mesh.num_devices > 1:
            assert res.all_reduce_cost_dict
            alpha, beta = cal.alpha_beta("all_reduce")
            assert beta > 0


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestMeasuredStageProfiling:
    """Opt-in compile+time of candidate stages (ref ProfileWorker,
    stage_profiling.py:321)."""

    def test_profile_stage_cost_runs_candidate(self):
        import jax
        import jax.numpy as jnp

        from alpa_tpu.mesh_profiling import profile_stage_cost
        from alpa_tpu.pipeline_parallel.computation import (
            JaxPipelineComputation)
        from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption

        def f(x, w):
            return jnp.tanh(x @ w) @ w

        closed = jax.make_jaxpr(f)(jnp.zeros((64, 64)), jnp.zeros((64, 64)))
        comp = JaxPipelineComputation(
            "probe", list(closed.jaxpr.invars), list(closed.jaxpr.outvars),
            list(closed.jaxpr.eqns))
        t1 = profile_stage_cost([comp], 1, AutoShardingOption())
        t8 = profile_stage_cost([comp], 8, AutoShardingOption())
        assert t1 > 0 and t8 > 0

    def test_measured_mode_refines_and_still_correct(self):
        """AutoStageOption(profiling_mode='measured') end-to-end: the DP
        runs on (partially) measured costs; numerics stay correct."""
        import alpa_tpu
        from alpa_tpu.pipeline_parallel.layer_construction import (
            AutoLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            AutoStageOption)
        from alpa_tpu.testing import (assert_allclose,
                                      create_mlp_train_state_and_batch,
                                      get_mlp_train_step)

        alpa_tpu.init(cluster="local")
        state_p, batch = create_mlp_train_state_and_batch(
            batch_size=32, num_layers=4, manual_pipeline_layer=False)
        state_s, _ = create_mlp_train_state_and_batch(
            batch_size=32, num_layers=4, manual_pipeline_layer=False)
        method = alpa_tpu.PipeshardParallel(
            num_micro_batches=2,
            layer_option=AutoLayerOption(layer_num=2),
            stage_option=AutoStageOption(profiling_mode="measured",
                                         measured_candidates_limit=6))
        pstep = get_mlp_train_step(method, use_value_and_grad=True)
        serial = get_mlp_train_step(None)
        state_p, loss_p = pstep(state_p, batch)
        state_s, loss_s = serial(state_s, batch)
        assert_allclose(float(loss_s), float(loss_p), 2e-3, 2e-3)
