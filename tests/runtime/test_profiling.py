"""Mesh profiling: measurement, fitting, persistence, calibrated costs.

The measured-DB path is VERDICT r1 #2: cost-model decisions must trace to
measurements, not abstract units (ref mesh_profiling.py:392-725).
"""
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu.device_mesh import LogicalDeviceMesh, get_global_cluster
from alpa_tpu.mesh_profiling import (MeshProfilingResult,
                                     ProfilingResultDatabase,
                                     profile_one_mesh)


def _synthetic_result(sec_per_flop=1e-12, sec_per_byte=1e-9):
    res = MeshProfilingResult()
    for flops in (1e6, 1e9):
        res.record("dot", ("f32",), flops, flops * sec_per_flop)
    for kind in ("all_reduce", "all_gather", "reduce_scatter",
                 "all_to_all"):
        for nbytes in (1e3, 1e6):
            res.record(kind, ("f32", 8), nbytes,
                       1e-5 + nbytes * sec_per_byte)
    return res


class TestProfilingDatabase:

    def test_fit_recovers_alpha_beta(self):
        cal = _synthetic_result().fit()
        for kind in ("all_reduce", "all_gather"):
            alpha, beta = cal.alpha_beta(kind)
            assert alpha == pytest.approx(1e-5, rel=1e-3)
            assert beta == pytest.approx(1e-9, rel=1e-3)
        assert cal.sec_per_flop(1e9) == pytest.approx(1e-12, rel=1e-6)

    def test_json_roundtrip(self, tmp_path):
        db = ProfilingResultDatabase()
        db.update_one_mesh("1x8-cpu", _synthetic_result())
        path = str(tmp_path / "db.json")
        db.save(path)
        db2 = ProfilingResultDatabase.load(path)
        res = db2.query("1x8-cpu")
        assert res is not None
        assert res.estimate("dot", ("f32",), 1e9) == pytest.approx(1e-3)
        cal = db2.best_result().fit()
        assert cal.alpha_beta("all_to_all") is not None

    def test_calibrated_logical_mesh_costs_are_seconds(self):
        cal = _synthetic_result().fit()
        mesh = LogicalDeviceMesh(None, np.arange(8).reshape(1, 8),
                                 calibration=cal)
        assert mesh.calibrated
        # 1 MB all-reduce on 8 devices: alpha + beta * 2 * 7/8 * 1e6
        got = mesh.all_reduce_cost(1e6, 1)
        want = 1e-5 + 1e-9 * 2 * (7 / 8) * 1e6
        assert got == pytest.approx(want, rel=1e-3)
        # uncalibrated mesh keeps abstract units (tie-break constants)
        abstract = LogicalDeviceMesh(None, np.arange(8).reshape(1, 8))
        assert abstract.all_reduce_cost(1e6, 1) > 1.0

    @pytest.mark.slow
    def test_profile_one_mesh_measures(self):
        """Real measurement on the 8-device CPU mesh: dots + collectives
        recorded, fits positive."""
        alpa_tpu.init("local")
        mesh = get_global_cluster().get_physical_mesh()
        res = profile_one_mesh(mesh, sizes=(1 << 14, 1 << 16),
                               dot_ns=(256, 512))
        assert res.dot_cost_dict
        cal = res.fit()
        assert cal.sec_per_flop(2 * 512**3) > 0
        if mesh.num_devices > 1:
            assert res.all_reduce_cost_dict
            alpha, beta = cal.alpha_beta("all_reduce")
            assert beta > 0


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestMeasuredStageProfiling:
    """Opt-in compile+time of candidate stages (ref ProfileWorker,
    stage_profiling.py:321)."""

    def test_profile_stage_cost_runs_candidate(self):
        import jax
        import jax.numpy as jnp

        from alpa_tpu.mesh_profiling import profile_stage_cost
        from alpa_tpu.pipeline_parallel.computation import (
            JaxPipelineComputation)
        from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption

        def f(x, w):
            return jnp.tanh(x @ w) @ w

        closed = jax.make_jaxpr(f)(jnp.zeros((64, 64)), jnp.zeros((64, 64)))
        comp = JaxPipelineComputation(
            "probe", list(closed.jaxpr.invars), list(closed.jaxpr.outvars),
            list(closed.jaxpr.eqns))
        t1 = profile_stage_cost([comp], 1, AutoShardingOption())
        t8 = profile_stage_cost([comp], 8, AutoShardingOption())
        assert t1 > 0 and t8 > 0

    def test_shortlist_buckets_cover_spans(self):
        """Shortlisting is per (span, submesh) bucket (ADVICE r2): long
        spans get measured too, not only the globally cheapest
        single-layer entries."""
        from alpa_tpu.mesh_profiling import shortlist_candidates
        L, M = 6, 2
        costs = np.zeros((L, L, M))
        for i in range(L):
            for j in range(i, L):
                for m in range(M):
                    if j < i:
                        continue
                    costs[i, j, m] = (j - i + 1) * (1.0 + m)
        cands = shortlist_candidates(costs, [1, 2], 8, limit=16)
        assert len(cands) == 16
        spans = {j - i for _c, i, j, _m in cands}
        assert len(spans) >= 4, spans  # not just span 0
        meshes = {m for _c, _i, _j, m in cands}
        assert meshes == {0, 1}

    def test_refine_raises_when_all_candidates_fail(self, monkeypatch):
        """Failures are surfaced (r2 weak #5: exceptions silently kept the
        model cost); a fully-broken measured mode raises."""
        import alpa_tpu.mesh_profiling as mp

        def boom(*a, **k):
            raise RuntimeError("no compile")

        monkeypatch.setattr(mp, "compile_stage_candidate", boom)
        costs = np.ones((2, 2, 1))
        with pytest.raises(RuntimeError, match="all"):
            mp.refine_costs_measured(costs, [None, None], [1], None,
                                     limit=2)

    def test_compute_cost_cache_roundtrip(self, tmp_path):
        """cached_compute_cost: a second auto_stage_dp run loads the
        tensors from disk and picks the same partition; a stale key
        recomputes (ref compute-cost-<time>.npy, stage_profiling.py:53)."""
        from alpa_tpu.pipeline_parallel.stage_dp import (
            compute_cost_cache_key, load_compute_cost_cache,
            save_compute_cost_cache)

        key = "abc123"
        costs = np.random.rand(3, 3, 2)
        mp_, ma_ = np.random.rand(3, 3, 2), np.random.rand(3, 3, 2)
        path = str(tmp_path / "cc.npz")
        save_compute_cost_cache(path, key, costs, mp_, ma_)
        got = load_compute_cost_cache(path, key, (3, 3, 2))
        assert got is not None
        np.testing.assert_array_equal(got[0], costs)
        # stale key or wrong shape -> miss
        assert load_compute_cost_cache(path, "otherkey", (3, 3, 2)) is None
        assert load_compute_cost_cache(path, key, (4, 4, 2)) is None

    def test_compute_cost_cache_key_sensitivity(self):
        """The key must change when the memory budget becomes active, the
        DB file changes, or the calibration content changes (ADVICE r3):
        a no-budget cache stores all-zero memory tensors and must not be
        reused under a budget."""
        from alpa_tpu.mesh_profiling import CalibratedCostModel
        from alpa_tpu.pipeline_parallel.stage_dp import (
            compute_cost_cache_key)

        comps, choices = [], [(1, 1), (1, 2)]
        base = compute_cost_cache_key(comps, choices, "cost_model")
        assert compute_cost_cache_key(comps, choices, "cost_model") == base
        assert compute_cost_cache_key(
            comps, choices, "cost_model", with_memory=True) != base
        assert compute_cost_cache_key(
            comps, choices, "cost_model", db_file="other.json") != base
        cal_a = CalibratedCostModel([(1e9, 1e-12)], {"all_reduce": (1e-5,
                                                                    1e-10)})
        cal_b = CalibratedCostModel([(1e9, 2e-12)], {"all_reduce": (1e-5,
                                                                    1e-10)})
        ka = compute_cost_cache_key(comps, choices, "cost_model",
                                    calibration=cal_a)
        kb = compute_cost_cache_key(comps, choices, "cost_model",
                                    calibration=cal_b)
        assert ka != kb and ka != base
        # span-cost strategy and sharding options shape the tensor too
        assert compute_cost_cache_key(
            comps, choices, "cost_model", exact_ilp=True) != \
            compute_cost_cache_key(comps, choices, "cost_model",
                                   exact_ilp=False)
        from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
        assert compute_cost_cache_key(
            comps, choices, "cost_model",
            sharding_option=AutoShardingOption()) != \
            compute_cost_cache_key(
                comps, choices, "cost_model",
                sharding_option=AutoShardingOption(
                    prefer_reduce_scatter=True))

    def test_cached_compute_cost_end_to_end(self, tmp_path):
        """Full pipeshard compile with cached_compute_cost set: first run
        writes the cache, second run (fresh executable) reads it and
        produces the same stage split."""
        import alpa_tpu
        from alpa_tpu.pipeline_parallel.layer_construction import (
            AutoLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            AutoStageOption)
        from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                      get_mlp_train_step)

        path = str(tmp_path / "compute_cost.npz")

        def run():
            alpa_tpu.init(cluster="local")
            state, batch = create_mlp_train_state_and_batch(
                batch_size=32, num_layers=4, manual_pipeline_layer=False)
            method = alpa_tpu.PipeshardParallel(
                num_micro_batches=2,
                layer_option=AutoLayerOption(layer_num=2),
                stage_option=AutoStageOption(cached_compute_cost=path))
            step = get_mlp_train_step(method, use_value_and_grad=True)
            step(state, batch)
            ex = step.get_last_executable()
            return ex.num_meshes

        n1 = run()
        assert pytest.importorskip("os").path.exists(path)
        alpa_tpu.shutdown()
        n2 = run()
        assert n1 == n2

    def test_measured_mode_refines_and_still_correct(self):
        """AutoStageOption(profiling_mode='measured') end-to-end: the DP
        runs on (partially) measured costs; numerics stay correct."""
        import alpa_tpu
        from alpa_tpu.pipeline_parallel.layer_construction import (
            AutoLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            AutoStageOption)
        from alpa_tpu.testing import (assert_allclose,
                                      create_mlp_train_state_and_batch,
                                      get_mlp_train_step)

        alpa_tpu.init(cluster="local")
        state_p, batch = create_mlp_train_state_and_batch(
            batch_size=32, num_layers=4, manual_pipeline_layer=False)
        state_s, _ = create_mlp_train_state_and_batch(
            batch_size=32, num_layers=4, manual_pipeline_layer=False)
        method = alpa_tpu.PipeshardParallel(
            num_micro_batches=2,
            layer_option=AutoLayerOption(layer_num=2),
            stage_option=AutoStageOption(profiling_mode="measured",
                                         measured_candidates_limit=6))
        pstep = get_mlp_train_step(method, use_value_and_grad=True)
        serial = get_mlp_train_step(None)
        state_p, loss_p = pstep(state_p, batch)
        state_s, loss_s = serial(state_s, batch)
        assert_allclose(float(loss_s), float(loss_p), 2e-3, 2e-3)
