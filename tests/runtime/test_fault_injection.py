"""CPU fault-injection tests: hung/failing probes, failed cross-mesh
transfers, retry/backoff bounds, and the full recovery state machine
(HEALTHY -> SUSPECT -> RECOVERING -> HEALTHY | DEGRADED).

Everything here runs on the virtual 8-device CPU mesh — the point of
``alpa_tpu.fault`` is that every recovery path is testable without a
TPU, let alone a broken one.  See docs/fault_tolerance.md.
"""
import threading
import time
import types

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alpa_tpu import fault
from alpa_tpu.fault import (FaultPlan, FaultSpec, InjectedFault, MeshHealth,
                            RecoveryManager, RetryPolicy)
from alpa_tpu.monitoring import FailureWatchdog, check_alive

pytestmark = pytest.mark.fault

FAST = RetryPolicy(max_attempts=2, base_delay=0.005, max_delay=0.02,
                   jitter=0.0)


@pytest.fixture(autouse=True)
def _clean_fault_state():
    yield
    fault.set_retry_policy(None)
    for site in list(fault._SITE_POLICIES):
        fault.set_retry_policy(None, site=site)
    fault.retry_stats.clear()


class _FakeMesh:
    """Just enough mesh for check_alive: one real CPU device."""

    def __init__(self):
        self.flat_devices = [jax.devices("cpu")[0]]

    def __repr__(self):
        return "FakeMesh"


class TestFaultPlan:

    def test_error_injection_counts_and_events(self):
        with FaultPlan(FaultSpec("s", times=2)) as plan:
            for _ in range(2):
                with pytest.raises(InjectedFault):
                    fault.fire("s", k=1)
            fault.fire("s", k=1)  # exhausted: no-op
        assert plan.hits("s") == 3
        assert plan.fired("s") == 2
        assert [e[0] for e in plan.events] == ["s", "s"]
        assert plan.events[0][2] == {"k": 1}

    def test_after_skips_first_hits(self):
        with FaultPlan(FaultSpec("s", after=2, times=1)) as plan:
            fault.fire("s")
            fault.fire("s")
            with pytest.raises(InjectedFault):
                fault.fire("s")
        assert plan.fired("s") == 1

    def test_match_targets_one_mesh(self):
        spec = FaultSpec("s", times=-1,
                         match=lambda info: info.get("mesh_id") == 1)
        with FaultPlan(spec) as plan:
            fault.fire("s", mesh_id=0)
            with pytest.raises(InjectedFault):
                fault.fire("s", mesh_id=1)
        assert plan.fired("s") == 1

    def test_slow_delays_then_continues(self):
        with FaultPlan(FaultSpec("s", kind="slow", delay=0.05)):
            t0 = time.monotonic()
            fault.fire("s")
            assert time.monotonic() - t0 >= 0.05

    def test_no_plan_is_noop_and_uninstrumented(self):
        fault.fire("anything", x=1)
        assert not fault.instrumented()
        with FaultPlan():
            assert fault.instrumented()

    def test_custom_exception_factory(self):
        with FaultPlan(FaultSpec("s", exc=lambda: OSError("wire"))):
            with pytest.raises(OSError):
                fault.fire("s")


class TestRetryPolicy:

    def test_backoff_is_bounded_exponential(self):
        pol = RetryPolicy(max_attempts=6, base_delay=0.01, multiplier=2.0,
                          max_delay=0.05, jitter=0.0)
        delays = [pol.backoff(k) for k in range(1, 6)]
        assert delays[:3] == [0.01, 0.02, 0.04]
        assert delays[3] == 0.05 and delays[4] == 0.05  # capped

    def test_jitter_stays_within_fraction(self):
        pol = RetryPolicy(base_delay=0.01, multiplier=1.0, jitter=0.5)
        for k in range(1, 50):
            d = pol.backoff(1)
            assert 0.01 <= d <= 0.015 + 1e-12

    def test_site_overrides(self):
        pol = RetryPolicy(max_attempts=5,
                          site_overrides={"probe": RetryPolicy(
                              max_attempts=1)})
        assert pol.for_site("probe").max_attempts == 1
        assert pol.for_site("other").max_attempts == 5

    def test_call_with_retry_recovers_from_injection(self):
        calls = []

        def op():
            fault.fire("op")
            calls.append(1)
            return 42

        with FaultPlan(FaultSpec("op", times=2)) as plan:
            out = fault.call_with_retry(
                op, policy=RetryPolicy(max_attempts=3, base_delay=0.001,
                                       jitter=0.0), site="op")
        assert out == 42 and len(calls) == 1
        assert plan.retries["op"] == 2
        assert len(plan.backoffs["op"]) == 2
        assert fault.retry_stats["op"] == 2

    def test_exhaustion_reraises_last_error(self):
        with FaultPlan(FaultSpec("op", times=-1)):
            with pytest.raises(InjectedFault):
                fault.call_with_retry(
                    lambda: fault.fire("op"),
                    policy=RetryPolicy(max_attempts=3, base_delay=0.001,
                                       jitter=0.0), site="op")

    def test_non_idempotent_real_error_not_retried(self):
        calls = []

        def op():
            calls.append(1)
            raise ValueError("real failure after side effects")

        with pytest.raises(ValueError):
            fault.call_with_retry(
                op, policy=RetryPolicy(max_attempts=5, base_delay=0.001),
                site="op", idempotent=False)
        assert len(calls) == 1  # never blindly re-run

    def test_injected_fault_retryable_even_when_non_idempotent(self):
        calls = []

        def op():
            fault.fire("op")  # fires BEFORE the real operation
            calls.append(1)
            return "ok"

        with FaultPlan(FaultSpec("op", times=1)):
            out = fault.call_with_retry(
                op, policy=RetryPolicy(max_attempts=2, base_delay=0.001,
                                       jitter=0.0),
                site="op", idempotent=False)
        assert out == "ok" and len(calls) == 1

    def test_deadline_budget_stops_retrying(self):
        t0 = time.monotonic()
        with FaultPlan(FaultSpec("op", times=-1)):
            with pytest.raises(InjectedFault):
                fault.call_with_retry(
                    lambda: fault.fire("op"),
                    policy=RetryPolicy(max_attempts=100, base_delay=0.02,
                                       multiplier=1.0, jitter=0.0,
                                       deadline=0.1),
                    site="op")
        assert time.monotonic() - t0 < 1.0

    def test_installed_policy_resolution(self):
        fault.set_retry_policy(RetryPolicy(max_attempts=7), site="x")
        assert fault.get_retry_policy("x").max_attempts == 7
        assert fault.get_retry_policy("y").max_attempts == 1  # NO_RETRY
        fault.set_retry_policy(None, site="x")
        assert fault.get_retry_policy("x").max_attempts == 1


class TestCheckAlive:

    def test_healthy_mesh_passes(self):
        assert check_alive(_FakeMesh(), timeout=10.0)

    def test_hung_probe_detected_within_timeout(self):
        """A wedged device (probe thread never returns) is reported dead
        after ~timeout, not hung forever — the abandoned-thread design."""
        with FaultPlan(FaultSpec("probe", kind="hang", delay=1.5)):
            t0 = time.monotonic()
            assert check_alive(_FakeMesh(), timeout=0.2) is False
            assert time.monotonic() - t0 < 1.0

    def test_probe_exception_means_dead(self):
        with FaultPlan(FaultSpec("probe")):
            assert check_alive(_FakeMesh(), timeout=1.0) is False

    def test_probe_retry_policy_rides_out_transient(self):
        with FaultPlan(FaultSpec("probe", times=1)) as plan:
            assert check_alive(_FakeMesh(), timeout=5.0,
                               retry_policy=FAST) is True
        assert plan.retries["probe"] == 1


class TestRecoveryStateMachine:
    """The acceptance scenario: HEALTHY -> SUSPECT -> RECOVERING ->
    HEALTHY with bounded retries, plus the DEGRADED paths."""

    def _manager(self, mesh, **kw):
        calls = {"quiesce": 0, "resume": 0, "snapshot": 0,
                 "degrade": [], "recover": 0}
        rm = RecoveryManager(
            [mesh], retry_policy=FAST,
            probe=lambda m: check_alive(m, timeout=0.3),
            quiesce=lambda: calls.__setitem__(
                "quiesce", calls["quiesce"] + 1),
            resume=lambda: calls.__setitem__(
                "resume", calls["resume"] + 1),
            snapshot=lambda: calls.__setitem__(
                "snapshot", calls["snapshot"] + 1),
            on_degrade=lambda reason: calls["degrade"].append(reason),
            on_recover=lambda: calls.__setitem__(
                "recover", calls["recover"] + 1),
            **kw)
        return rm, calls

    def test_full_recovery_cycle_with_bounded_retries(self):
        """Probe fails long enough to reach RECOVERING (quiesce +
        snapshot fire), then clears: the machine walks HEALTHY ->
        SUSPECT -> RECOVERING -> HEALTHY and every re-probe attempt is
        accounted and bounded."""
        mesh = _FakeMesh()
        rm, calls = self._manager(mesh)
        # hit 1: watchdog round probe; hits 2-3: SUSPECT re-probe
        # (max_attempts=2); hit 4: recovery probe -> clean
        with FaultPlan(FaultSpec("probe", times=3)) as plan:
            state = rm.tick()
        assert state is MeshHealth.HEALTHY
        assert [(o.value, n.value) for o, n, _ in rm.transitions] == [
            ("healthy", "suspect"), ("suspect", "recovering"),
            ("recovering", "healthy")]
        assert calls["quiesce"] == 1 and calls["snapshot"] == 1
        assert calls["resume"] == 1 and calls["recover"] == 1
        assert calls["degrade"] == []
        assert rm.snapshots_taken == 1
        # bounded: exactly 4 probe attempts, with the extra attempts
        # recorded per retry site
        assert plan.hits("probe") == 4
        assert plan.retries["probe"] == 1
        assert plan.retries.get("recovery_probe") is None

    def test_transient_blip_recovers_at_suspect(self):
        mesh = _FakeMesh()
        rm, calls = self._manager(mesh)
        with FaultPlan(FaultSpec("probe", times=1)):
            state = rm.tick()
        assert state is MeshHealth.HEALTHY
        assert calls["quiesce"] == 0  # never reached RECOVERING
        assert [(o.value, n.value) for o, n, _ in rm.transitions] == [
            ("healthy", "suspect"), ("suspect", "healthy")]

    def test_unrecoverable_degrades_then_heals(self):
        mesh = _FakeMesh()
        rm, calls = self._manager(mesh)
        with FaultPlan(FaultSpec("probe", times=-1)):
            state = rm.tick()
            assert state is MeshHealth.DEGRADED
            assert calls["degrade"], "on_degrade must fire"
            # stays degraded while the mesh is still dead
            assert rm.tick() is MeshHealth.DEGRADED
        # fault lifted: the next clean round restores service
        assert rm.tick() is MeshHealth.HEALTHY
        assert calls["recover"] >= 1 and calls["resume"] >= 1

    def test_hooks_may_raise_without_killing_the_machine(self):
        mesh = _FakeMesh()
        rm = RecoveryManager(
            [mesh], retry_policy=FAST,
            probe=lambda m: check_alive(m, timeout=0.3),
            quiesce=lambda: 1 / 0,
            on_degrade=lambda reason: 1 / 0)
        with FaultPlan(FaultSpec("probe", times=-1)):
            assert rm.tick() is MeshHealth.DEGRADED
        assert rm.tick() is MeshHealth.HEALTHY

    def test_watchdog_drives_recovery_from_its_thread(self):
        mesh = _FakeMesh()
        rm, calls = self._manager(mesh)
        wd = FailureWatchdog([mesh], interval=0.02, recovery=rm,
                             probe_timeout=0.3)
        seen_failure = []
        wd.on_failure = lambda dead: seen_failure.append(list(dead))
        with FaultPlan(FaultSpec("probe", times=-1)):
            wd.start()
            deadline = time.monotonic() + 20.0
            while (rm.state is not MeshHealth.DEGRADED and
                   time.monotonic() < deadline):
                time.sleep(0.02)
            assert rm.state is MeshHealth.DEGRADED
            assert seen_failure and seen_failure[0] == [0]
        # plan exited: watchdog's next clean round recovers
        deadline = time.monotonic() + 20.0
        while (rm.state is not MeshHealth.HEALTHY and
               time.monotonic() < deadline):
            time.sleep(0.02)
        wd.stop()
        assert rm.state is MeshHealth.HEALTHY

    def test_snapshotter_writes_restorable_checkpoint(self, tmp_path):
        from alpa_tpu.serialization import restore_checkpoint
        state = {"w": jnp.arange(4.0), "step": jnp.asarray(7)}
        snap = fault.make_snapshotter(str(tmp_path), lambda: state)
        rm = RecoveryManager([_FakeMesh()], retry_policy=FAST,
                             probe=lambda m: check_alive(m, timeout=0.3),
                             snapshot=snap)
        with FaultPlan(FaultSpec("probe", times=3)):
            assert rm.tick() is MeshHealth.HEALTHY
        assert rm.snapshots_taken == 1
        restored = restore_checkpoint(str(tmp_path), state)
        np.testing.assert_array_equal(np.asarray(restored["w"]),
                                      np.arange(4.0))


class TestCrossMeshTransferFaults:

    def test_failed_transfer_retried_to_success(self):
        """The pipeshard RESHARD contract: a transfer that fails once is
        re-run under the ``cross_mesh_send`` retry site and lands."""
        from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
            ReshardingTask)
        dst = jax.sharding.SingleDeviceSharding(jax.devices("cpu")[1])
        task = ReshardingTask(types.SimpleNamespace(requests=[]), dst)
        arr = jnp.arange(8.0)
        with FaultPlan(FaultSpec("cross_mesh_recv", times=1)) as plan:
            out = fault.call_with_retry(
                lambda: task.run(arr),
                policy=RetryPolicy(max_attempts=3, base_delay=0.001,
                                   jitter=0.0),
                site="cross_mesh_send")
        np.testing.assert_array_equal(np.asarray(out), np.arange(8.0))
        assert list(out.devices())[0] == jax.devices("cpu")[1]
        assert plan.fired("cross_mesh_recv") == 1
        assert plan.retries["cross_mesh_send"] == 1


class TestPipeshardFaults:
    """End-to-end through the real pipeshard runtime on the 8-device
    CPU mesh: stage launches retry through injected faults, and
    quiesce/resume gate in-flight work."""

    def _build(self):
        import alpa_tpu
        from alpa_tpu import PipeshardParallel
        from alpa_tpu.pipeline_parallel.layer_construction import (
            ManualLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            UniformStageOption)
        from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                      get_mlp_train_step)
        alpa_tpu.init(cluster="local")
        state, batch = create_mlp_train_state_and_batch(
            batch_size=64, num_layers=4, manual_pipeline_layer=True)
        step = get_mlp_train_step(PipeshardParallel(
            num_micro_batches=2,
            layer_option=ManualLayerOption(),
            stage_option=UniformStageOption(num_stages=2),
            pipeline_schedule="1f1b"), use_value_and_grad=True)
        return state, batch, step

    def test_stage_launch_fault_is_retried(self):
        state, batch, step = self._build()
        state, loss0 = step(state, batch)  # compile clean
        fault.set_retry_policy(
            RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0),
            site="stage_launch")
        try:
            with FaultPlan(FaultSpec("stage_launch", times=1)) as plan:
                state, loss1 = step(state, batch)
            assert plan.fired("stage_launch") == 1
            assert plan.retries["stage_launch"] == 1
        finally:
            fault.set_retry_policy(None, site="stage_launch")
        assert np.isfinite(float(loss1))
        # and the step after the fault plan is gone still works
        _, loss2 = step(state, batch)
        assert np.isfinite(float(loss2))

    def test_quiesce_blocks_new_launches_until_resume(self):
        state, batch, step = self._build()
        state, _ = step(state, batch)
        ex = step.get_last_executable()
        ex.quiesce(timeout=10.0)
        started = threading.Event()
        done = threading.Event()
        result = {}

        def blocked_step():
            started.set()
            result["out"] = step(state, batch)
            done.set()

        t = threading.Thread(target=blocked_step, daemon=True)
        t.start()
        started.wait(5.0)
        # gate closed: the launch must not complete
        assert not done.wait(0.3)
        ex.resume()
        assert done.wait(30.0), "resume() must release queued launches"
        assert np.isfinite(float(result["out"][1]))
