"""One hook-instrumented graph executor for all dispatch modes
(ISSUE 6 tentpole).

Oracle 1: numerics — with instrumentation ON (span tracing, a firing
fault site) the register and overlap graph replays must stay bit-exact
vs the sequential interpreter; instrumentation is compiled into the
replay plan as per-node hooks, not a reason to fall back.  Oracle 2:
mode selection — ``auto`` keeps the fast path under ``collect_trace``
(the tier-1 no-interpreter-fallback guard) and produces a valid
multi-track Chrome trace.  Oracle 3: the flight recorder — ring
wraparound, dump-on-exception, `trace_tool.py flight` readability.
Plus the static lowering-time hazard pass (`graph.check()`), the
runtime `SlotHazardChecker` hook, and the hooked-overhead regression
bound.
"""
import json
import os

import numpy as np
import pytest

import alpa_tpu
import jax
from alpa_tpu import PipeshardParallel, fault
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.telemetry import flight as tflight
from alpa_tpu.telemetry import trace as ttrace
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _restore_globals():
    prev_mode = global_config.pipeline_dispatch_mode
    prev_collect = global_config.collect_trace
    prev_flight = global_config.flight_recorder
    yield
    global_config.pipeline_dispatch_mode = prev_mode
    global_config.collect_trace = prev_collect
    global_config.flight_recorder = prev_flight
    fault.set_retry_policy(None)


def _run_steps(mode, n_steps=2):
    global_config.pipeline_dispatch_mode = mode
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=4),
        stage_option=UniformStageOption(num_stages=4))
    step = get_mlp_train_step(method, use_value_and_grad=False)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)
    val = None
    for _ in range(n_steps):
        state, val = step(state, batch)
    return state, val, step.get_last_executable()


def _assert_bitwise_equal(states_vals):
    (state_a, val_a), *rest = states_vals
    leaves_a = jax.tree_util.tree_leaves(state_a.params)
    assert leaves_a
    for state_b, val_b in rest:
        leaves_b = jax.tree_util.tree_leaves(state_b.params)
        assert len(leaves_a) == len(leaves_b)
        for x, y in zip(leaves_a, leaves_b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
        np.testing.assert_array_equal(np.asarray(val_a), np.asarray(val_b))


# ---------------------------------------------------------------------
# bit-exactness with instrumentation on
# ---------------------------------------------------------------------

def test_three_way_bitwise_with_tracing_on():
    """Registers and overlap stay bit-exact vs the interpreter with the
    trace hook compiled in — the replay plan changed, the numerics must
    not."""
    alpa_tpu.init("local")
    global_config.collect_trace = True
    ttrace.get_recorder().clear()
    state_s, val_s, ex_s = _run_steps("sequential")
    state_r, val_r, ex_r = _run_steps("registers")
    state_o, val_o, ex_o = _run_steps("overlap")
    assert ex_s.last_dispatch_stats["mode"] == "sequential"
    assert ex_r.last_dispatch_stats["mode"] == "registers"
    assert ex_o.last_dispatch_stats["mode"] == "overlap"
    assert "trace" in ex_r.last_dispatch_stats["hooks"]
    assert "trace" in ex_o.last_dispatch_stats["hooks"]
    _assert_bitwise_equal([(state_s, val_s), (state_r, val_r),
                           (state_o, val_o)])
    ttrace.get_recorder().clear()


def test_three_way_bitwise_with_firing_fault_site():
    """A stage_launch fault that fires once and is retried must leave
    every mode's numerics untouched — the fault hook preempts the real
    execution, so the retry replays an op that never ran."""
    alpa_tpu.init("local")
    fault.set_retry_policy(fault.RetryPolicy(max_attempts=3,
                                             base_delay=0.0))
    out = {}
    for mode in ("sequential", "registers", "overlap"):
        plan = fault.FaultPlan(
            fault.FaultSpec("stage_launch", kind="error", times=1))
        with plan:
            state, val, ex = _run_steps(mode)
        st = ex.last_dispatch_stats
        assert st["mode"] == mode, st
        assert plan.fired("stage_launch") == 1, (mode, plan.events)
        assert plan.retries.get("stage_launch", 0) >= 1, (mode,
                                                          plan.retries)
        if mode != "sequential":
            assert "fault" in st["hooks"], st
        out[mode] = (state, val)
    _assert_bitwise_equal([out["sequential"], out["registers"],
                           out["overlap"]])


def test_fault_site_hit_parity_with_interpreter():
    """Armed-but-never-firing sites must see the same number of
    matching fire() calls from the graph executor as from the
    interpreter — hook emission covers every RUN and cross-mesh
    RESHARD, including grouped ops (one fire per member)."""
    alpa_tpu.init("local")
    hits = {}
    for mode in ("sequential", "registers", "overlap"):
        plan = fault.FaultPlan(
            fault.FaultSpec("stage_launch", kind="error", after=10**9),
            fault.FaultSpec("cross_mesh_send", kind="error",
                            after=10**9))
        with plan:
            _run_steps(mode, n_steps=1)
        hits[mode] = (plan.hits("stage_launch"),
                      plan.hits("cross_mesh_send"))
    assert hits["registers"] == hits["sequential"], hits
    assert hits["overlap"] == hits["sequential"], hits
    assert hits["sequential"][0] > 0 and hits["sequential"][1] > 0, hits


# ---------------------------------------------------------------------
# tier-1 guard: `auto` no longer falls back to the interpreter
# ---------------------------------------------------------------------

def test_auto_keeps_fast_path_under_collect_trace():
    """The three-way mode fork is gone: with collect_trace=True, auto
    still lowers to the register/overlap graph executor and the dumped
    Chrome trace is valid and multi-track."""
    alpa_tpu.init("local")
    global_config.collect_trace = True
    ttrace.get_recorder().clear()
    _, _, ex = _run_steps("auto", n_steps=1)
    st = ex.last_dispatch_stats
    assert st["mode"] in ("registers", "overlap"), st
    assert st["mode"] not in ("sequential", "threaded"), st
    assert "trace" in st["hooks"], st

    trace = ttrace.get_recorder().to_chrome_trace()
    events = trace["traceEvents"]
    spans = [e for e in events if e.get("ph") == "X"]
    begins = [e for e in events if e.get("ph") == "B"]
    named = spans or begins
    assert named, "collect_trace produced no instruction spans"
    names = {e["name"] for e in named}
    assert any(n.startswith("RUN") for n in names), names
    # multi-track: instructions land on distinct per-mesh tracks
    tids = {e.get("tid") for e in named}
    assert len(tids) > 1, tids
    ttrace.get_recorder().clear()


# ---------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------

def test_flight_ring_wraparound(tmp_path):
    rec = tflight.FlightRecorder(capacity=7)   # rounds up to 8
    assert rec.capacity == 8
    for i in range(20):
        rec.record("exec", f"RUN s{i}", i % 4, i, (i,), 10 * i,
                   10 * i + 5, "ok")
    evs = rec.snapshot()
    assert len(evs) == 8
    assert [e[0] for e in evs] == list(range(12, 20))   # last 8 seqs
    path = rec.dump(str(tmp_path / "flight.json"), reason="unit test")
    dump = tflight.load_dump(path)
    assert dump["reason"] == "unit test"
    assert dump["n_events"] == 8
    assert dump["first_seq"] == 12 and dump["last_seq"] == 19
    assert dump["events"][-1]["name"] == "RUN s19"


def test_flight_dump_on_step_exception(tmp_path):
    """An uncaught mid-step error auto-dumps the ring, and the dump is
    readable by the trace_tool flight subcommand."""
    alpa_tpu.init("local")
    global_config.flight_recorder = True
    global_config.flight_dump_dir = str(tmp_path)
    prev_rec = tflight.set_recorder(tflight.FlightRecorder(capacity=256))
    fault.set_retry_policy(None)    # NO_RETRY: the fault escapes
    try:
        plan = fault.FaultPlan(
            fault.FaultSpec("stage_launch", kind="error", times=1))
        with plan:
            with pytest.raises(fault.InjectedFault):
                _run_steps("registers", n_steps=1)
        path = tflight.last_dump_path()
        assert path is not None and os.path.dirname(path) == str(tmp_path)
        dump = tflight.load_dump(path)
        assert dump["events"], dump
        # the fault fired on the step's first instruction (empty ring at
        # fire time), so the step-raise trigger produced the dump; its
        # ring holds the failed instruction with its error outcome
        assert dump["reason"] in ("pipeshard step raised",
                                  "fault site fired: stage_launch "
                                  "(error)"), dump["reason"]
        outcomes = {e["outcome"] for e in dump["events"]}
        assert "error:InjectedFault" in outcomes, outcomes
        kinds = {e["kind"] for e in dump["events"]}
        assert "exec" in kinds
        import importlib
        trace_tool = importlib.import_module("scripts.trace_tool")
        trace_tool.main(["flight", path, "--last", "5"])
    finally:
        tflight.set_recorder(prev_rec)
        global_config.flight_dump_dir = None


def test_flight_hook_records_instruction_events():
    alpa_tpu.init("local")
    global_config.flight_recorder = True
    prev_rec = tflight.set_recorder(tflight.FlightRecorder(capacity=1024))
    try:
        _, _, ex = _run_steps("overlap", n_steps=1)
        st = ex.last_dispatch_stats
        assert "flight" in st["hooks"], st
        evs = tflight.get_recorder().snapshot()
        assert evs, "flight hook recorded nothing"
        names = {e[2] for e in evs}
        assert any(n.startswith("RUN") for n in names), names
        outcomes = {e[8] for e in evs}
        assert outcomes == {"ok"}, outcomes
    finally:
        tflight.set_recorder(prev_rec)


# ---------------------------------------------------------------------
# hazard checking: static pass + runtime hook
# ---------------------------------------------------------------------

def test_graph_check_passes_on_real_lowering():
    alpa_tpu.init("local")
    _, _, ex = _run_steps("registers", n_steps=1)
    prog = ex._register_programs["registers"]
    assert prog.graph is not None
    prog.graph.check()   # must not raise on a real compile


def test_graph_check_catches_broken_edges():
    """Corrupting the dependence edges of a real lowering must trip the
    static hazard pass with a slot-level diagnosis."""
    import dataclasses

    alpa_tpu.init("local")
    _, _, ex = _run_steps("registers", n_steps=1)
    graph = ex._register_programs["registers"].graph
    # drop every predecessor of a node that reads slots: now some read
    # has no edge to its writer (RAW) or a FREE loses its transfer edge
    victim = next(i for i, n in enumerate(graph.nodes)
                  if n.reads and graph.preds[i])
    broken_preds = list(graph.preds)
    broken_preds[victim] = ()
    broken = dataclasses.replace(graph, preds=broken_preds)
    with pytest.raises(RuntimeError, match="hazard|edge|slot"):
        broken.check()


def test_slot_hazard_checker_flags_bad_interleavings():
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        OpHook, SlotHazardChecker)

    def hook(kind, node, reads=(), writes=(), kills=()):
        return OpHook(kind=kind, name=f"n{node}", node=node, mesh=0,
                      reads=tuple(reads), writes=tuple(writes),
                      kills=tuple(kills),
                      slots=tuple(reads) + tuple(writes) + tuple(kills))

    # clean run: launch -> wait -> consume
    chk = SlotHazardChecker()
    chk.begin_step()
    chk.on_launch(hook("launch", 0, reads=[1], writes=[2]))
    chk.on_wait(hook("wait", 0, reads=[1], writes=[2]))
    chk.on_exec(hook("exec", 1, reads=[2]))
    chk.check()

    # read of an in-flight destination
    chk.begin_step()
    chk.on_launch(hook("launch", 0, reads=[1], writes=[2]))
    chk.on_exec(hook("exec", 1, reads=[2]))
    with pytest.raises(RuntimeError):
        chk.check()

    # FREE of an in-flight source
    chk.begin_step()
    chk.on_launch(hook("launch", 0, reads=[1], writes=[2]))
    chk.on_exec(hook("exec", 1, kills=[1]))
    with pytest.raises(RuntimeError):
        chk.check()


def test_race_hook_clean_on_real_program():
    """debug_dispatch_races is now a graph-node hook: a real lowering
    replayed with it enabled stays clean and stays on the fast path."""
    alpa_tpu.init("local")
    prev = global_config.debug_dispatch_races
    global_config.debug_dispatch_races = True
    try:
        _, _, ex = _run_steps("overlap", n_steps=2)
        st = ex.last_dispatch_stats
        assert st["mode"] == "overlap", st
        assert "race" in st["hooks"], st
    finally:
        global_config.debug_dispatch_races = prev


# ---------------------------------------------------------------------
# overhead regression: hooked < 2x unhooked register replay
# ---------------------------------------------------------------------

@pytest.mark.slow
def test_hooked_overhead_under_two_x():
    """Per-instruction cost with every hook class compiled in (trace +
    armed fault sites + flight) must stay under 2x the raw register
    replay — hooks are per-node closures, not an interpreter."""
    alpa_tpu.init("local")
    from benchmark.bench_dispatch import run_hooked
    r = run_hooked(n_steps=5)
    assert set(r["hooks_on"]) == {"trace", "fault", "flight"}, r
    assert r["hooks_on_per_inst_us"] < 2.0 * r["hooks_off_per_inst_us"], r


def test_hooked_overhead_artifact_bound():
    """The committed benchmark artifact must show hooked-mode overhead
    under the 2x bound (regenerated by benchmark/bench_dispatch.py)."""
    path = os.path.join(REPO, "benchmark", "results",
                        "dispatch_modes.json")
    with open(path, encoding="utf-8") as f:
        artifact = json.load(f)
    hooked = artifact.get("hooked")
    assert hooked is not None, \
        "dispatch_modes.json predates the hooked executor — " \
        "regenerate with benchmark/bench_dispatch.py"
    assert hooked["hooks_on_per_inst_us"] < \
        2.0 * hooked["hooks_off_per_inst_us"], hooked
    assert set(hooked["hooks_on"]) == {"trace", "fault", "flight"}
