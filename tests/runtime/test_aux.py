"""Aux subsystems: checkpoint save/restore, data loader, parallel plan,
create-state / follow methods (ref tests/runtime/, SURVEY.md §4.6)."""
import os
import tempfile

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import alpa_tpu
from alpa_tpu import DataParallel, ShardParallel, Zero3Parallel
from alpa_tpu.create_state_parallel import CreateStateParallel
from alpa_tpu.data_loader import DataLoader, get_batch_shardings
from alpa_tpu.follow_parallel import FollowParallel
from alpa_tpu.parallel_plan import (ParallelPlan, executable_to_plan,
                                    plan_to_method)
from alpa_tpu.serialization import (checkpoint_wait, restore_checkpoint,
                                    save_checkpoint)
from alpa_tpu.testing import (assert_allclose, create_mlp_train_state_and_batch,
                              get_mlp_train_step)


class TestCheckpoint:

    def test_save_restore_roundtrip(self, tmp_path):
        state, batch = create_mlp_train_state_and_batch()
        step = get_mlp_train_step(Zero3Parallel(), use_value_and_grad=True)
        state, _ = step(state, batch)  # state now sharded
        ckpt = str(tmp_path / "ckpt")
        save_checkpoint(ckpt, state.params, step=1)
        checkpoint_wait()
        target = jax.tree_util.tree_map(jnp.zeros_like,
                                        jax.device_get(state.params))
        restored = restore_checkpoint(ckpt, target)
        assert_allclose(jax.device_get(state.params), restored)

    def test_cross_topology_restore(self, tmp_path):
        """Save sharded one way, restore with a different sharding."""
        mesh8 = Mesh(np.array(jax.devices()).reshape(8), ("x",))
        x = jax.device_put(jnp.arange(64.0).reshape(8, 8),
                           NamedSharding(mesh8, P("x")))
        ckpt = str(tmp_path / "ckpt2")
        save_checkpoint(ckpt, {"w": x}, step=0)
        checkpoint_wait()
        mesh4 = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("a", "b"))
        new_sharding = NamedSharding(mesh4, P(None, "b"))
        restored = restore_checkpoint(
            ckpt, {"w": jnp.zeros((8, 8))}, {"w": new_sharding})
        assert_allclose(np.asarray(restored["w"]), np.asarray(x))
        assert restored["w"].sharding.is_equivalent_to(new_sharding, 2)

    def test_local_cache_drain(self, tmp_path):
        state, _ = create_mlp_train_state_and_batch()
        ckpt = str(tmp_path / "final")
        cache = str(tmp_path / "cache")
        save_checkpoint(ckpt, {"p": jnp.ones((4, 4))}, step=0,
                        local_cache_dir=cache)
        checkpoint_wait()
        restored = restore_checkpoint(ckpt, {"p": jnp.zeros((4, 4))})
        assert_allclose(np.asarray(restored["p"]), np.ones((4, 4)))


class TestDataLoader:

    def test_prefetching_loader_places_batches(self):
        state, batch = create_mlp_train_state_and_batch(batch_size=16)
        step = get_mlp_train_step(DataParallel(), use_value_and_grad=True)
        state, _ = step(state, batch)
        ex = step.get_last_executable()
        # shardings of the two batch leaves (x, y) in flat order
        batch_shardings = [
            s for s, a in zip(ex.in_shardings, ex.in_avals)
            if a.shape[:1] == (16,)
        ]

        def it():
            for i in range(4):
                yield {
                    "x": np.full((16, 32), i, np.float32),
                    "y": np.full((16, 32), i, np.float32),
                }

        loader = DataLoader(it, {"x": batch_shardings[0],
                                 "y": batch_shardings[1]},
                            prefetch_size=2)
        count = 0
        for placed in loader:
            assert isinstance(placed["x"], jax.Array)
            assert placed["x"].sharding.is_equivalent_to(
                batch_shardings[0], 2)
            state, _ = step(state, placed)
            count += 1
        assert count == 4


class TestDistributedDataLoader:

    def test_loads_only_addressable_rows(self):
        """Per-shard callback loading (ref MeshWorkerDataLoader:229): each
        shard's rows are requested exactly once; the assembled global
        array matches the logical batch."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from alpa_tpu.data_loader import DistributedDataLoader

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
        sharding = NamedSharding(mesh, P("dp", None))
        requested = []

        def next_batch_fn(step):
            def row_loader(start, stop):
                requested.append((step, start, stop))
                rows = np.arange(start, stop, dtype=np.float32)
                return (np.full((stop - start, 4), step, np.float32) +
                        rows[:, None])
            return row_loader

        loader = DistributedDataLoader((16, 4), sharding, next_batch_fn,
                                       num_batches=3)
        batches = list(loader)
        assert len(batches) == 3
        for step, b in enumerate(batches):
            assert isinstance(b, jax.Array)
            want = step + np.arange(16, dtype=np.float32)[:, None] + \
                np.zeros((16, 4), np.float32)
            assert_allclose(np.asarray(b), want)
        # 8 shards x 2 rows each, per batch — never the full batch at once
        per_step = [(s, a, b) for (s, a, b) in requested if s == 0]
        assert len(per_step) == 8
        assert all(b - a == 2 for (_, a, b) in per_step)

    def test_loader_errors_propagate(self):
        """A failing row loader must raise in the consumer, not silently
        truncate the epoch."""
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        from alpa_tpu.data_loader import DistributedDataLoader

        mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
        sharding = NamedSharding(mesh, P("dp", None))

        def next_batch_fn(step):
            def row_loader(start, stop):
                if step == 1:
                    raise IOError("shard file missing")
                return np.zeros((stop - start, 4), np.float32)
            return row_loader

        loader = DistributedDataLoader((16, 4), sharding, next_batch_fn,
                                       num_batches=3)
        got = []
        with pytest.raises(IOError, match="shard file missing"):
            for b in loader:
                got.append(b)
        assert len(got) == 1


class TestParallelPlan:

    def test_plan_roundtrip(self, tmp_path):
        state, batch = create_mlp_train_state_and_batch()
        step = get_mlp_train_step(ShardParallel(), use_value_and_grad=True)
        state, _ = step(state, batch)
        plan = executable_to_plan(step.get_last_executable())
        fn = str(tmp_path / "plan.pkl")
        plan.save(fn)
        loaded = ParallelPlan.load(fn)
        method = plan_to_method(loaded)
        # replay: compiles without search and matches numerics
        state2, _ = create_mlp_train_state_and_batch()
        step2 = get_mlp_train_step(method, use_value_and_grad=True)
        s_a, _ = step2(state2, batch)
        assert s_a is not None


class TestCreateStateAndFollow:

    def test_create_state_sharded_init(self):
        state, batch = create_mlp_train_state_and_batch()
        train_step = get_mlp_train_step(Zero3Parallel(),
                                        use_value_and_grad=True)
        # prime the executable
        s1, _ = train_step(state, batch)

        import optax
        from flax.training import train_state as ts

        from alpa_tpu.testing import MLPModel

        model = MLPModel(hidden_dim=32, output_dim=32, num_layers=2)

        def create_state():
            rng = jax.random.PRNGKey(0)
            params = model.init(rng, jnp.ones((64, 32)))
            return ts.TrainState.create(apply_fn=model.apply, params=params,
                                        tx=optax.sgd(1e-2, momentum=0.9))

        method = CreateStateParallel(train_step, (state, batch))
        init_fn = alpa_tpu.parallelize(create_state, method=method,
                                       batch_argnums=())
        new_state = init_fn()
        # leaves must come back sharded like the train step inputs
        ex = train_step.get_last_executable()
        flat_new = jax.tree_util.tree_leaves(new_state)
        n_state = len(flat_new)
        for x, s in zip(flat_new, ex.in_shardings[:n_state]):
            if hasattr(x, "sharding"):
                assert x.sharding.is_equivalent_to(s, np.ndim(x))

    def test_follow_parallel_eval_step(self):
        state, batch = create_mlp_train_state_and_batch()
        train_step = get_mlp_train_step(ShardParallel(),
                                        use_value_and_grad=True)
        state, _ = train_step(state, batch)

        def eval_step(state, batch):
            out = state.apply_fn(state.params, batch["x"])
            return ((out - batch["y"])**2).mean(axis=-1)

        method = FollowParallel(train_step, (state, batch))
        efn = alpa_tpu.parallelize(eval_step, method=method)
        losses = efn(state, batch)
        ref = eval_step(state, batch)
        assert_allclose(np.asarray(losses), np.asarray(ref), 1e-4, 1e-4)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestMonitoring:

    def test_check_alive_and_dump(self, tmp_path):
        from alpa_tpu.device_mesh import LocalPhysicalDeviceMesh
        from alpa_tpu.monitoring import check_alive, dump_debug_info

        mesh = LocalPhysicalDeviceMesh()
        assert check_alive(mesh)

        state, batch = create_mlp_train_state_and_batch()
        step = get_mlp_train_step(DataParallel(), use_value_and_grad=True)
        step(state, batch)
        d = str(tmp_path / "dump")
        dump_debug_info(step.get_last_executable(), d)
        assert (tmp_path / "dump" / "compiled_hlo.txt").exists()
