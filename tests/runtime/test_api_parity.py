"""Top-level API parity with the reference's ``alpa/__init__.py``
exports: a user switching from the reference finds every public name
(ref __init__.py:23-49), and the compat shims actually function.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import alpa_tpu

REF_TOP_LEVEL = [
    # alpa/__init__.py:23-49
    "init", "shutdown", "parallelize", "grad", "value_and_grad",
    "clear_executable_cache", "DataLoader", "MeshDriverDataLoader",
    "DeviceCluster", "PhysicalDeviceMesh", "LocalPhysicalDeviceMesh",
    "DistributedPhysicalDeviceMesh", "DistributedArray", "prefetch",
    "get_global_cluster", "get_global_physical_mesh",
    "get_global_virtual_physical_mesh",
    "set_global_virtual_physical_mesh", "set_seed",
    "get_global_num_devices", "global_config",
    "ProfilingResultDatabase", "ShardParallel", "DataParallel",
    "Zero2Parallel", "Zero3Parallel", "PipeshardParallel",
    "CreateStateParallel", "FollowParallel", "get_3d_parallel_method",
    "plan_to_method", "mark_pipeline_boundary", "manual_remat",
    "automatic_remat", "ManualLayerOption", "AutoLayerOption",
    "ManualStageOption", "AutoStageOption", "UniformStageOption",
    "AutoShardingOption", "ManualShardingOption", "save_checkpoint",
    "restore_checkpoint", "timers",
]


def test_every_reference_export_exists():
    missing = [n for n in REF_TOP_LEVEL if not hasattr(alpa_tpu, n)]
    assert not missing, missing


def test_remat_decorators_preserve_numerics():
    def loss(w, x):
        h = jnp.tanh(x @ w)
        h = jnp.tanh(h @ w)
        return jnp.sum(h ** 2)

    w = jnp.asarray(np.random.RandomState(0).randn(8, 8), jnp.float32)
    x = jnp.asarray(np.random.RandomState(1).randn(4, 8), jnp.float32)
    want_v = loss(w, x)
    want_g = jax.grad(loss)(w, x)

    auto = alpa_tpu.automatic_remat(loss, layer_num=2)
    np.testing.assert_allclose(np.asarray(auto(w, x)),
                               np.asarray(want_v), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(jax.grad(auto)(w, x)),
                               np.asarray(want_g), rtol=1e-5, atol=1e-6)

    from alpa_tpu import mark_pipeline_boundary

    def marked(w, x):
        h = jnp.tanh(x @ w)
        mark_pipeline_boundary()
        h = jnp.tanh(h @ w)
        return jnp.sum(h ** 2)

    man = alpa_tpu.manual_remat(marked)
    np.testing.assert_allclose(np.asarray(man(w, x)),
                               np.asarray(want_v), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(jax.grad(man)(w, x)),
                               np.asarray(want_g), rtol=1e-5, atol=1e-6)


def test_clear_executable_cache_forces_recompile():
    alpa_tpu.init(cluster="local")

    @alpa_tpu.parallelize(method=alpa_tpu.DataParallel())
    def step(state, batch):
        def loss_fn(p):
            return jnp.mean((batch["x"] @ p) ** 2)
        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state)
        return state - 0.1 * grads, loss

    w = jnp.ones((8, 4))
    batch = {"x": jnp.ones((16, 8))}
    _, l1 = step(w, batch)
    ex1 = step.get_last_executable()
    alpa_tpu.clear_executable_cache()
    _, l2 = step(w, batch)
    ex2 = step.get_last_executable()
    assert ex1 is not ex2
    np.testing.assert_allclose(float(l1), float(l2))


def test_prefetch_and_num_devices():
    arrs = {"a": jnp.ones((4, 4)), "b": [jnp.zeros((2,))]}
    alpa_tpu.prefetch(arrs)  # must not raise
    assert alpa_tpu.get_global_num_devices() == len(jax.devices())


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
