"""Per-tick driver dispatch stays sub-millisecond at 8 meshes
(SURVEY §7 hard part #5; VERDICT r4 next #7).

Near-zero-FLOP payloads make the threaded instruction loop's wall time
the driver cost itself — see scripts/dispatch_overhead_bench.py, which
records the committed artifact with the same measurement.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def test_dispatch_under_1ms_per_instruction_at_8_meshes():
    from scripts.dispatch_overhead_bench import measure

    stats = measure(n_steps=5)
    assert stats["mode"] == "threaded"
    assert stats["n_meshes"] == 8
    assert stats["per_inst_us"] < 1000, stats
