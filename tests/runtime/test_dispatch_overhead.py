"""Per-tick driver dispatch stays sub-millisecond at 8 meshes
(SURVEY §7 hard part #5; VERDICT r4 next #7).

Near-zero-FLOP payloads make the instruction loop's wall time the driver
cost itself — see scripts/dispatch_overhead_bench.py, which records the
committed artifact with the same measurement.  Since ISSUE 2 the default
mode ("auto") replays the build-time register-file lowering, so the
measured mode is "registers"; the interpreter bound is kept as a
regression guard via an explicit mode override.
"""
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, REPO)


def test_dispatch_under_1ms_per_instruction_at_8_meshes():
    from scripts.dispatch_overhead_bench import measure

    stats = measure(n_steps=5)
    # auto upgrades to overlap on this multi-mesh payload (ISSUE 4);
    # the sub-ms driver-cost bound applies to either replay mode
    assert stats["mode"] in ("overlap", "registers")
    assert stats["n_meshes"] == 8
    assert stats["per_inst_us"] < 1000, stats


def test_register_dispatch_beats_interpreter():
    """The register fast path must stay ahead of the sequential
    interpreter on the same payload (ISSUE 2 tentpole)."""
    from scripts.dispatch_overhead_bench import measure

    reg = measure(n_steps=5, dispatch_mode="registers")
    seq = measure(n_steps=5, dispatch_mode="sequential")
    assert reg["mode"] == "registers"
    assert seq["mode"] == "sequential"
    # generous bound: steady-state is ~3x; CI noise should never push a
    # genuinely faster path past parity
    assert reg["per_inst_us"] < seq["per_inst_us"], (reg, seq)
