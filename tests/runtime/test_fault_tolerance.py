"""End-to-end fault tolerance: checkpoint -> crash -> resume (SURVEY §5
checkpoint/resume + failure detection exercised TOGETHER as one flow,
not as isolated unit tests).

A pipelined training run checkpoints mid-flight in a child process,
"crashes" (the process exits hard), and a fresh process restores the
sharded checkpoint and finishes — final parameters matching the
uninterrupted run within reduction-order tolerance (the restore lands
on a DIFFERENT parallel method, so post-resume float reductions
associate differently; cross-topology restore is what a real recovery
after losing part of a cluster looks like).
"""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

WORKER = r"""
import sys

from alpa_tpu.platform import pin_cpu_platform
pin_cpu_platform(8)
import jax
import jax.numpy as jnp
import numpy as np

import alpa_tpu
from alpa_tpu.pipeline_parallel.layer_construction import ManualLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.serialization import (checkpoint_wait, restore_checkpoint,
                                    save_checkpoint)
from alpa_tpu.testing import create_mlp_train_state_and_batch, \
    get_mlp_train_step

mode, ckpt, out = sys.argv[1], sys.argv[2], sys.argv[3]
alpa_tpu.init(cluster="local")

def make_step(n_stages):
    method = alpa_tpu.PipeshardParallel(
        num_micro_batches=2, layer_option=ManualLayerOption(),
        stage_option=UniformStageOption(num_stages=n_stages))
    return get_mlp_train_step(method, use_value_and_grad=True)

state, batch = create_mlp_train_state_and_batch(
    batch_size=64, num_layers=4, manual_pipeline_layer=True)

if mode == "uninterrupted":
    step = make_step(2)
    for _ in range(8):
        state, loss = step(state, batch)
elif mode == "crash":
    step = make_step(2)
    for _ in range(4):
        state, loss = step(state, batch)
    save_checkpoint(ckpt, {"params": state.params,
                           "opt_state": state.opt_state}, step=4)
    checkpoint_wait()
    sys.stdout.write("CHECKPOINTED\n")
    sys.stdout.flush()
    os_exit = getattr(__import__("os"), "_exit")
    os_exit(1)  # hard crash: no cleanup, like a host loss
elif mode == "resume":
    # recovery on a DIFFERENT topology: 1 stage (intra-op only) instead
    # of the original 2-stage pipeline
    from alpa_tpu.serialization import load_checkpoint_metadata
    target = {"params": jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), state.params),
        "opt_state": jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if hasattr(x, "shape") else x, state.opt_state)}
    restored = restore_checkpoint(ckpt, target)
    assert load_checkpoint_metadata(ckpt)["step"] == 4
    state = state.replace(params=restored["params"],
                          opt_state=restored["opt_state"])
    step = make_step(1)
    for _ in range(4):
        state, loss = step(state, batch)

if mode in ("uninterrupted", "resume"):
    flat = jax.tree_util.tree_leaves(jax.device_get(state.params))
    np.savez(out, *[np.asarray(x) for x in flat])
    sys.stdout.write("DONE\n")
"""


def _run(mode, ckpt, out, expect_rc=0):
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS="",
               PYTHONPATH=REPO_ROOT)
    r = subprocess.run([sys.executable, "-c", WORKER, mode, ckpt, out],
                       env=env, capture_output=True, text=True,
                       timeout=600)
    assert r.returncode == expect_rc, (
        f"{mode}: rc={r.returncode}\n{r.stderr[-2000:]}")
    return r.stdout


def test_crash_resume_matches_uninterrupted(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    base = _run("uninterrupted", ckpt, str(tmp_path / "base.npz"))
    out = _run("crash", ckpt, "-", expect_rc=1)
    assert "CHECKPOINTED" in out  # died AFTER the checkpoint landed
    out = _run("resume", ckpt, str(tmp_path / "resumed.npz"))
    assert "DONE" in out

    a = np.load(tmp_path / "base.npz")
    b = np.load(tmp_path / "resumed.npz")
    assert len(a.files) == len(b.files) and len(a.files) > 0
    for f in a.files:
        np.testing.assert_allclose(a[f], b[f], rtol=2e-3, atol=2e-3)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
