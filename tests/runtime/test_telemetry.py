"""Unified telemetry layer (ISSUE 5 tentpole).

Oracle 1: the Chrome trace a traced multi-mesh pipeshard train step
exports is schema-valid — every ``E`` closes a matching ``B`` on its
track, instruction/transfer/checkpoint spans land on distinct named
tracks, and the multi-trace merge keeps per-process track groups.
Oracle 2: the metrics registry — exact counts under concurrent
increments, correct percentiles on a known distribution, valid
Prometheus text exposition.  Oracle 3: zero-cost-when-off — the
disabled path allocates nothing (shared null-span singleton) and the
register-dispatch replay pays <2% overhead vs the raw op loop.
"""
import collections
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import alpa_tpu
from alpa_tpu.global_env import global_config
from alpa_tpu.telemetry import metrics as tmetrics
from alpa_tpu.telemetry import trace as ttrace
from alpa_tpu.telemetry.trace import TraceRecorder, merge_chrome_traces

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture
def fresh_trace():
    """Fresh recorder + tracing on; restores both afterwards."""
    rec = TraceRecorder()
    old_rec = ttrace.set_recorder(rec)
    prev = ttrace.set_enabled(True)
    yield rec
    ttrace.set_enabled(prev)
    ttrace.set_recorder(old_rec)


def _check_chrome_schema(trace):
    """Every E closes a matching B on its (pid, tid); returns the
    per-track completed span names."""
    assert "traceEvents" in trace
    spans_by_track = collections.defaultdict(list)
    stacks = collections.defaultdict(list)
    events = sorted(
        (e for e in trace["traceEvents"] if e.get("ph") in ("B", "E")),
        key=lambda e: (e["ts"], 0 if e["ph"] == "E" else 1))
    for e in events:
        key = (e.get("pid", 0), e["tid"])
        if e["ph"] == "B":
            assert isinstance(e["name"], str) and e["ts"] >= 0
            stacks[key].append(e)
        else:
            assert stacks[key], f"E without open B on track {key}: {e}"
            b = stacks[key].pop()
            assert e["ts"] >= b["ts"]
            spans_by_track[key].append(b["name"])
    dangling = {k: [e["name"] for e in v] for k, v in stacks.items() if v}
    assert not dangling, f"unclosed B events: {dangling}"
    return spans_by_track


def _track_names(trace):
    """tid -> thread_name from the metadata events."""
    return {e["tid"]: e["args"]["name"]
            for e in trace["traceEvents"]
            if e.get("ph") == "M" and e.get("name") == "thread_name"}


# ---------------------------------------------------------------------
# span recorder basics
# ---------------------------------------------------------------------

class TestTraceRecorder:

    def test_nested_spans_and_instants(self, fresh_trace):
        with ttrace.span("outer", "runtime", {"k": 1}):
            with ttrace.span("inner", "compile"):
                pass
        ttrace.instant("tick", "legacy", {"info": "x"})
        ttrace.counter("inflight", 2)
        trace = fresh_trace.to_chrome_trace()
        by_track = _check_chrome_schema(trace)
        all_names = [n for names in by_track.values() for n in names]
        assert set(all_names) == {"outer", "inner"}
        phs = collections.Counter(e["ph"] for e in trace["traceEvents"])
        assert phs["i"] == 1 and phs["C"] == 1 and phs["M"] >= 2

    def test_begin_end_cross_thread(self, fresh_trace):
        tok = ttrace.begin("async-op", "transfer", None, "pool")

        def closer():
            ttrace.end(tok)

        t = threading.Thread(target=closer)
        t.start()
        t.join()
        spans = fresh_trace.spans()
        assert [s["name"] for s in spans] == ["async-op"]
        assert spans[0]["track"] == "pool"

    def test_tids_stable_per_track(self, fresh_trace):
        for _ in range(3):
            with ttrace.span("a", "runtime", None, "mesh 0"):
                pass
            with ttrace.span("b", "runtime", None, "mesh 1"):
                pass
        spans = fresh_trace.spans()
        tids = {s["track"]: {x["tid"] for x in spans
                             if x["track"] == s["track"]}
                for s in spans}
        assert all(len(v) == 1 for v in tids.values())
        assert tids["mesh 0"] != tids["mesh 1"]

    def test_max_events_drops_and_reports(self, fresh_trace):
        fresh_trace.max_events = 10
        for i in range(50):
            with ttrace.span(f"s{i}", "runtime"):
                pass
        trace = fresh_trace.to_chrome_trace()
        _check_chrome_schema(trace)
        assert trace["alpa_dropped_events"] == 40

    def test_merge_assigns_distinct_pids(self, fresh_trace):
        with ttrace.span("one", "runtime"):
            pass
        t1 = fresh_trace.to_chrome_trace()
        merged = merge_chrome_traces([t1, t1])
        pids = {e["pid"] for e in merged["traceEvents"]}
        assert pids == {0, 1}
        _check_chrome_schema(merged)

    def test_save_is_valid_json(self, fresh_trace, tmp_path):
        with ttrace.span("one", "runtime"):
            pass
        path = tmp_path / "trace.json"
        fresh_trace.save(str(path))
        with open(path, encoding="utf-8") as f:
            trace = json.load(f)
        _check_chrome_schema(trace)


# ---------------------------------------------------------------------
# zero-cost-when-off
# ---------------------------------------------------------------------

class TestDisabledMode:

    def test_null_span_is_shared_singleton(self):
        assert not ttrace.enabled()
        assert ttrace.span("a") is ttrace.span("b")
        assert ttrace.begin("a") is None
        ttrace.end(None)  # no-op
        ttrace.instant("x")
        ttrace.counter("x", 1.0)

    def test_disabled_records_nothing(self, fresh_trace):
        ttrace.set_enabled(False)
        with ttrace.span("invisible", "runtime"):
            pass
        ttrace.instant("invisible")
        assert fresh_trace.n_events == 0

    def test_register_replay_overhead_under_guard(self):
        """The disabled fast path checks the enabled flag ONCE per step:
        replaying a big synthetic register program through execute()
        must stay within 2% of the raw op loop."""
        from alpa_tpu.pipeline_parallel.runtime_emitter import (
            RegisterFileProgram)
        assert not ttrace.enabled()
        n_ops = 20000
        sink = [0]

        def op(regs, _sink=sink):
            _sink[0] += 1

        ops = [op] * n_ops
        prog = RegisterFileProgram(
            num_slots=1, ops=ops, n_instructions=n_ops,
            by_opcode={"RUN": n_ops}, slot_of={}, n_coalesced_groups=0,
            n_fixups=0, text="synthetic",
            op_meta=[("RUN synth", "instruction", "mesh 0")] * n_ops)
        regs = [None]

        def timed(fn):
            t0 = time.perf_counter()
            fn()
            return time.perf_counter() - t0

        def raw():
            for o in prog.ops:
                o(regs)

        # warm both paths
        raw()
        prog.execute(regs)
        # interleave the two measurements and take the best per-round
        # ratio: a genuine per-instruction cost in execute() would show
        # up in EVERY round, while one-sided scheduler jitter (the flaky
        # failure mode of timing two independent best-ofs) does not.
        ratio = min(
            timed(lambda: prog.execute(regs)) / timed(raw)
            for _ in range(15))
        assert ratio < 1.02, (
            f"disabled-telemetry replay overhead {ratio - 1:.2%} "
            f"exceeds the 2% guard over {n_ops} ops")

    def test_traced_replay_emits_op_spans(self, fresh_trace):
        from alpa_tpu.pipeline_parallel.runtime_emitter import (
            RegisterFileProgram)
        ops = [lambda regs: None] * 3
        prog = RegisterFileProgram(
            num_slots=1, ops=ops, n_instructions=3,
            by_opcode={"RUN": 3}, slot_of={}, n_coalesced_groups=0,
            n_fixups=0, text="synthetic",
            op_meta=[("RUN a", "instruction", "mesh 0"),
                     ("RESHARD 0->1", "instruction", "mesh 1"),
                     ("FREE", "instruction", "mesh 1")])
        prog.execute([None])
        names = [s["name"] for s in fresh_trace.spans()]
        assert names == ["RUN a", "RESHARD 0->1", "FREE"]


# ---------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------

class TestMetricsRegistry:

    def test_counter_concurrent_increments_exact(self):
        reg = tmetrics.MetricsRegistry()
        c = reg.counter("t_total", "test")
        h = reg.histogram("t_seconds", "test")
        n_threads, n_incs = 8, 2000

        def worker():
            for _ in range(n_incs):
                c.inc()
                h.observe(0.01)

        threads = [threading.Thread(target=worker)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert c.value == n_threads * n_incs
        assert h.summary()["count"] == n_threads * n_incs

    def test_histogram_percentiles_known_distribution(self):
        reg = tmetrics.MetricsRegistry()
        h = reg.histogram("lat", "test")
        for v in range(1, 101):        # 0.001 .. 0.100
            h.observe(v / 1000.0)
        assert abs(h.percentile(50) - 0.050) <= 0.001
        assert abs(h.percentile(95) - 0.095) <= 0.001
        assert abs(h.percentile(99) - 0.099) <= 0.001
        s = h.summary()
        assert s["count"] == 100
        assert abs(s["sum"] - sum(v / 1000.0 for v in range(1, 101))) \
            < 1e-9
        # cumulative buckets: everything <= 0.1 bucket, nothing <= 1ms
        # except the single 0.001 observation
        buckets = dict(h.bucket_counts())
        assert buckets[0.1] == 100
        assert buckets[0.001] == 1

    def test_labels_and_kind_mismatch(self):
        reg = tmetrics.MetricsRegistry()
        fam = reg.counter("hits", "test", labelnames=("ns",))
        fam.labels("ilp").inc(2)
        fam.labels("stage_dp").inc()
        vals = {k: c.value for k, c in fam.children()}
        assert vals == {("ilp",): 2, ("stage_dp",): 1}
        with pytest.raises(Exception):
            reg.gauge("hits")          # same name, different kind
        with pytest.raises(Exception):
            fam.labels("ilp").inc(-1)  # counters only go up

    def test_gauge_set_max(self):
        reg = tmetrics.MetricsRegistry()
        g = reg.gauge("hi", "test")
        g.set_max(5)
        g.set_max(3)
        assert g.value == 5

    def test_prometheus_text_exposition(self):
        reg = tmetrics.MetricsRegistry()
        reg.counter("req_total", "requests", ("code",)).labels("200").inc()
        reg.gauge("depth", "queue depth").set(7)
        reg.histogram("lat_seconds", "latency").observe(0.003)
        text = reg.to_prometheus_text()
        assert "# HELP req_total requests" in text
        assert "# TYPE req_total counter" in text
        assert 'req_total{code="200"} 1' in text
        assert "depth 7" in text
        assert 'lat_seconds_bucket{le="+Inf"} 1' in text
        assert "lat_seconds_count 1" in text
        assert "lat_seconds_sum 0.003" in text

    def test_collectors_fill_compile_cache_gauges(self):
        """The live global registry exposes compile-cache state through
        its collector even though the cache instance is swapped per
        test."""
        from alpa_tpu.compile_cache import get_compile_cache
        get_compile_cache()            # ensure a live instance
        text = tmetrics.get_registry().to_prometheus_text()
        assert "alpa_compile_cache_memory_entries" in text

    def test_thin_stat_views_keep_legacy_shapes(self):
        from alpa_tpu.checkpoint import metrics as ckpt_metrics
        from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
            get_planner_stats, reset_planner_stats)
        from alpa_tpu.pipeline_parallel.runtime_emitter import (
            get_overlap_runtime_stats, reset_overlap_runtime_stats)
        reset_overlap_runtime_stats()
        rt = get_overlap_runtime_stats()
        assert set(rt) == {"steps", "transfer_busy_s", "wait_blocked_s",
                           "n_hoisted", "n_launches",
                           "last_overlap_fraction", "last_window"}
        assert isinstance(rt["steps"], int)
        reset_planner_stats()
        pl = get_planner_stats()
        assert set(pl) == {"plans", "total_bytes", "broadcast_bytes",
                           "max_link_bytes", "max_link_bytes_naive"}
        ckpt_metrics.incr("saves")
        assert ckpt_metrics.snapshot()["saves"] == 1
        ckpt_metrics.reset()
        assert ckpt_metrics.snapshot() == {}


# ---------------------------------------------------------------------
# legacy Tracer bridge
# ---------------------------------------------------------------------

class TestTracerBridge:

    def test_log_mirrors_into_unified_trace(self, fresh_trace):
        from alpa_tpu.timer import Tracer
        tr = Tracer()
        tr.log("old-site", "info=1")
        # old API unchanged
        assert tr.events[-1].name == "old-site"
        assert tr.to_chrome_trace()[-1]["name"] == "old-site"
        # and mirrored as a legacy-category instant
        trace = fresh_trace.to_chrome_trace()
        inst = [e for e in trace["traceEvents"] if e.get("ph") == "i"]
        assert inst and inst[0]["name"] == "old-site"
        assert inst[0]["cat"] == "legacy"

    def test_log_without_tracing_stays_local(self, fresh_trace):
        from alpa_tpu.timer import Tracer
        ttrace.set_enabled(False)
        tr = Tracer()
        tr.log("quiet")
        assert tr.events[-1].name == "quiet"
        assert fresh_trace.n_events == 0


# ---------------------------------------------------------------------
# end-to-end: traced multi-mesh pipeshard train step
# ---------------------------------------------------------------------

class TestTracedPipeshard:

    def test_overlap_step_exports_valid_multi_track_trace(
            self, fresh_trace, tmp_path):
        """THE acceptance scenario: a traced overlap train step on
        multiple meshes + a checkpoint save exports ONE merged Chrome
        trace with instruction spans per mesh track, transfer-pool
        spans, and checkpoint spans — schema-valid everywhere."""
        from alpa_tpu import PipeshardParallel
        from alpa_tpu.checkpoint.manager import CheckpointManager
        from alpa_tpu.pipeline_parallel.layer_construction import (
            AutoLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            UniformStageOption)
        from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                      get_mlp_train_step)
        alpa_tpu.init("local")
        prev_mode = global_config.pipeline_dispatch_mode
        global_config.pipeline_dispatch_mode = "overlap"
        try:
            method = PipeshardParallel(
                num_micro_batches=2,
                layer_option=AutoLayerOption(layer_num=4),
                stage_option=UniformStageOption(num_stages=4))
            step = get_mlp_train_step(method, use_value_and_grad=False)
            state, batch = create_mlp_train_state_and_batch(
                batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
                num_layers=4, manual_pipeline_layer=False)
            for _ in range(2):
                state, val = step(state, batch)
            float(val)
            assert step.get_last_executable() \
                .last_dispatch_stats["mode"] == "overlap"
            mgr = CheckpointManager(str(tmp_path / "ckpt"))
            mgr.save(0, {"w": np.ones((4,), np.float32)}, sync=True)
        finally:
            global_config.pipeline_dispatch_mode = prev_mode

        trace = fresh_trace.to_chrome_trace()
        by_track = _check_chrome_schema(trace)
        names = _track_names(trace)
        tid_of = {v: k for k, v in names.items()}

        # instruction spans on >= 2 distinct mesh tracks
        mesh_tracks = [t for t in tid_of if t.startswith("mesh ")]
        assert len(mesh_tracks) >= 2, f"tracks: {sorted(tid_of)}"
        run_tracks = [t for t in mesh_tracks
                      if any(n.startswith("RUN")
                             for n in by_track[(0, tid_of[t])])]
        assert len(run_tracks) >= 2

        all_names = [n for v in by_track.values() for n in v]
        # transfer-pool spans (driver-side LAUNCH/WAIT + pool-side work)
        assert any(n.startswith(("LAUNCH", "WAIT")) for n in all_names)
        assert any(n.startswith("reshard.") for n in all_names)
        # checkpoint + step + compile spans in the SAME merged trace
        assert "checkpoint.save" in all_names
        assert "pipeshard.step" in all_names
        assert any(n in ("ilp-solve", "ilp-cache-replay")
                   for n in all_names)
        # the transfer in-flight window rides a counter track
        assert any(e.get("ph") == "C" and
                   e["name"] == "transfers_in_flight"
                   for e in trace["traceEvents"])
        # overlap registry metrics flowed
        text = tmetrics.get_registry().to_prometheus_text()
        assert "alpa_overlap_steps_total" in text
        assert "alpa_checkpoint_stat_total" in text

    def test_tracing_does_not_force_interpreter_fallback(self,
                                                         fresh_trace):
        """Unlike legacy collect_trace, span telemetry keeps the lowered
        fast paths."""
        from alpa_tpu import PipeshardParallel
        from alpa_tpu.pipeline_parallel.layer_construction import (
            AutoLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            UniformStageOption)
        from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                      get_mlp_train_step)
        alpa_tpu.init("local")
        prev_mode = global_config.pipeline_dispatch_mode
        global_config.pipeline_dispatch_mode = "registers"
        try:
            method = PipeshardParallel(
                num_micro_batches=2,
                layer_option=AutoLayerOption(layer_num=4),
                stage_option=UniformStageOption(num_stages=4))
            step = get_mlp_train_step(method, use_value_and_grad=False)
            state, batch = create_mlp_train_state_and_batch(
                batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
                num_layers=4, manual_pipeline_layer=False)
            state, val = step(state, batch)
            float(val)
            assert step.get_last_executable() \
                .last_dispatch_stats["mode"] == "registers"
        finally:
            global_config.pipeline_dispatch_mode = prev_mode
        assert any(s["name"].startswith("RUN")
                   for s in fresh_trace.spans())


# ---------------------------------------------------------------------
# trace_tool CLI
# ---------------------------------------------------------------------

class TestTraceTool:

    def _make_trace_file(self, path):
        rec = TraceRecorder()
        old_rec = ttrace.set_recorder(rec)
        prev = ttrace.set_enabled(True)
        try:
            for i in range(3):
                with ttrace.span(f"RUN stage{i}", "instruction", None,
                                 f"mesh {i}"):
                    time.sleep(0.001)
            with ttrace.span("plan", "compile"):
                pass
        finally:
            ttrace.set_enabled(prev)
            ttrace.set_recorder(old_rec)
        rec.save(str(path))

    def test_merge_summarize_top(self, tmp_path):
        a, b = tmp_path / "a.json", tmp_path / "b.json"
        self._make_trace_file(a)
        self._make_trace_file(b)
        merged = tmp_path / "merged.json"
        tool = os.path.join(REPO, "scripts", "trace_tool.py")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        r = subprocess.run(
            [sys.executable, tool, "merge", str(merged), str(a), str(b)],
            capture_output=True, text=True, env=env, check=True)
        assert "merged 2 trace file(s)" in r.stdout
        with open(merged, encoding="utf-8") as f:
            _check_chrome_schema(json.load(f))
        r = subprocess.run(
            [sys.executable, tool, "summarize", str(merged)],
            capture_output=True, text=True, env=env, check=True)
        assert "instruction" in r.stdout and "compile" in r.stdout
        r = subprocess.run(
            [sys.executable, tool, "top", str(merged), "--top", "3"],
            capture_output=True, text=True, env=env, check=True)
        assert "RUN stage0" in r.stdout
