"""Elastic training (ISSUE 16): detect -> quiesce -> snapshot ->
re-solve -> resume, end to end on the committed CPU fixtures.

The load-bearing checks mirror the acceptance criteria:

* an injected worker-kill on the 2-stage pipeshard fixture recovers —
  quiesce, snapshot, re-solve for the surviving half of the mesh,
  resume — within the step budget, with every post-resume loss
  **bitwise equal** to an uninterrupted run restored from the same
  step on the same surviving plan;
* a candidate plan whose verdict carries any NEW (analysis, code)
  finding is rejected and the supervisor rolls back to the old plan +
  last verified checkpoint (pinned negative test);
* retry exhaustion at an elastic fault site escalates to the recovery
  manager instead of propagating the raw error (pinned).

The dp=4->dp=2 live rescale and the >=20-seed kill-schedule fuzz live
in test_elastic_fuzz.py.  See docs/fault_tolerance.md#elastic-training.
"""
import os
import subprocess
import sys
import tempfile
import time

import jax
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu import elastic, fault
from alpa_tpu.checkpoint.manager import CheckpointManager
from alpa_tpu.device_mesh import VirtualPhysicalMesh
from alpa_tpu.elastic import (ElasticSupervisor, PreemptionNotice,
                              WedgeDetector, WorkerLost)
from alpa_tpu.pipeline_parallel.layer_construction import ManualLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import create_mlp_train_state_and_batch, \
    get_mlp_train_step

pytestmark = pytest.mark.fault

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


@pytest.fixture(autouse=True)
def _clean_elastic_state():
    yield
    fault.set_escalation_manager(None)
    elastic._ACTIVE = None


@pytest.fixture(autouse=True)
def _reset_ckpt_metrics():
    """Supervisor snapshots bump the process-global checkpoint counters
    test_telemetry pins; reset after each test."""
    from alpa_tpu.checkpoint import metrics
    yield
    metrics.reset()


def make_solve(num_stages=2):
    """Per-device-set memoized pipeshard solve — the supervisor's
    re-solve hook.  Memoization matters twice over: an episode whose
    survivors match the current set reuses the identical compiled
    executable (bitwise continuity for free), and the comparator run
    below gets the exact executable the supervisor hot-swapped to."""
    cache = {}

    def solve(devices):
        key = tuple(id(d) for d in devices)
        if key not in cache:
            n = len(devices)
            vm = VirtualPhysicalMesh(
                1, n, np.array(list(devices), dtype=object).reshape(1, n))
            method = alpa_tpu.PipeshardParallel(
                devices=vm, num_micro_batches=2,
                layer_option=ManualLayerOption(),
                stage_option=UniformStageOption(num_stages=num_stages))
            cache[key] = get_mlp_train_step(method,
                                            use_value_and_grad=True)
        return cache[key]

    return solve


def fresh_state_and_batch():
    # PRNGKey(0)-deterministic: every call returns bitwise-identical
    # initial state, so "recreate" == "copy" for comparator runs
    return create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)


def run_supervised(sup, batch, until, max_calls=50):
    """Drive sup.step until ``until`` steps commit; returns
    {step_index: loss} over every committed step."""
    losses = {}
    for _ in range(max_calls):
        if sup.step_index >= until:
            return losses
        loss = sup.step(batch)
        losses[sup.step_index] = np.asarray(loss)
    raise AssertionError(f"no progress: stuck at step {sup.step_index}")


class TestWedgeDetector:
    """The runbook's probe-timeout taxonomy, as unit checks (no mesh
    needed: the probe is injectable)."""

    def test_ok_wedged_dead(self):
        det = WedgeDetector(probe_timeout_s=0.2)
        det._probe = lambda mesh: True
        assert det.probe_one(object()) == "ok"
        det._probe = lambda mesh: time.sleep(5.0)
        assert det.probe_one(object()) == "wedged"

        def dead(mesh):
            raise RuntimeError("runtime gone")

        det._probe = dead
        assert det.probe_one(object()) == "dead"
        det._probe = lambda mesh: False
        assert det.probe_one(object()) == "dead"

    def test_sweep_short_circuits_after_first_wedge(self):
        probed = []

        def probe(mesh):
            probed.append(mesh)
            if mesh == "m1":
                time.sleep(5.0)
            return True

        det = WedgeDetector(mesh_group=["m0", "m1", "m2", "m3"],
                            probe=probe, probe_timeout_s=0.2)
        statuses = det.check()
        assert statuses == {0: "ok", 1: "wedged", 2: "skipped",
                            3: "skipped"}
        # the runbook discipline: never probe past a wedge
        assert probed == ["m0", "m1"]
        assert not det.healthy()

    def test_check_is_an_injection_point(self):
        det = WedgeDetector(mesh_group=[], probe_timeout_s=0.2)
        with fault.FaultPlan(fault.FaultSpec("wedge_detected")):
            with pytest.raises(fault.InjectedFault):
                det.check()


class TestEscalation:
    """Satellite 1 pinned behavior: retry exhaustion at an elastic site
    escalates to the recovery manager instead of propagating raw."""

    def test_exhaustion_escalates_to_recovery_manager(self):
        rm = fault.RecoveryManager()
        fault.set_escalation_manager(rm)

        def boom():
            raise RuntimeError("host gone")

        with pytest.raises(fault.ServiceDegradedError) as exc:
            fault.call_with_retry(
                boom, site="worker_lost",
                policy=fault.RetryPolicy(max_attempts=2, base_delay=0.001,
                                         max_delay=0.005, jitter=0.0))
        # chained, not swallowed: the root cause stays reachable
        assert isinstance(exc.value.__cause__, RuntimeError)
        # the manager entered (and possibly completed) recovery —
        # whatever it did, it is no longer idling in SUSPECT
        assert rm.state in (fault.MeshHealth.RECOVERING,
                            fault.MeshHealth.HEALTHY,
                            fault.MeshHealth.DEGRADED)

    def test_non_elastic_site_still_raises_raw(self):
        fault.set_escalation_manager(fault.RecoveryManager())

        def boom():
            raise RuntimeError("probe down")

        with pytest.raises(RuntimeError, match="probe down"):
            fault.call_with_retry(
                boom, site="probe",
                policy=fault.RetryPolicy(max_attempts=2, base_delay=0.001,
                                         max_delay=0.005, jitter=0.0))

    def test_no_manager_installed_raises_raw(self):
        def boom():
            raise RuntimeError("host gone")

        with pytest.raises(RuntimeError, match="host gone"):
            fault.call_with_retry(
                boom, site="worker_lost",
                policy=fault.RetryPolicy(max_attempts=2, base_delay=0.001,
                                         max_delay=0.005, jitter=0.0))

    def test_supervisor_escalation_queues_an_episode(self, tmp_path):
        """The supervisor registers itself as the escalation manager;
        an exhausted elastic-site retry becomes a queued episode the
        next step boundary drains."""
        alpa_tpu.init(cluster="local")
        state, batch = fresh_state_and_batch()
        step = get_mlp_train_step()  # plain jit: no pipeshard compile
        sup = ElasticSupervisor(lambda devices: step, state,
                                checkpoint_root=str(tmp_path))
        assert fault.get_escalation_manager() is sup

        def boom():
            raise RuntimeError("worker died")

        with pytest.raises(fault.ServiceDegradedError):
            fault.call_with_retry(
                boom, site="worker_lost",
                policy=fault.RetryPolicy(max_attempts=2, base_delay=0.001,
                                         max_delay=0.005, jitter=0.0))
        sup.step(batch)
        assert [e["reason"] for e in sup.episodes] == ["worker_lost"]
        assert sup.episodes[0]["replan"] == "reused"


class TestSupervisorPipeshard:

    def test_worker_kill_resolves_for_survivors_bitwise(self, tmp_path):
        """Acceptance: kill half the mesh at a step boundary; the
        supervisor re-solves a 2-stage plan over the surviving 4
        devices and every post-resume loss is bitwise-equal to an
        uninterrupted run restored from the same step on the same
        surviving plan."""
        alpa_tpu.init(cluster="local")
        solve = make_solve()
        state, batch = fresh_state_and_batch()
        sup = ElasticSupervisor(solve, state,
                                checkpoint_root=str(tmp_path))
        survivors = list(jax.devices())[:4]
        with fault.FaultPlan(fault.FaultSpec(
                "worker_lost", after=2,
                exc=lambda: WorkerLost(survivors=survivors))):
            losses = run_supervised(sup, batch, until=5)

        assert [e["reason"] for e in sup.episodes] == ["worker_lost"]
        ep = sup.episodes[0]
        assert ep["quiesced"] is True
        assert ep["snapshot"] == "boundary"
        assert ep["replan"] == "accepted"
        assert ep["devices_before"] == 8 and ep["devices_after"] == 4
        assert ep["within_step_budget"] and ep["within_time_budget"]
        assert sup.devices == survivors

        # /healthz surface
        report = elastic.status_report()
        assert report["devices"] == 4
        assert report["episodes"] == 1
        assert report["last_episode"]["reason"] == "worker_lost"
        assert report["recovering"] is False

        # comparator: restore the SAME step into the SAME surviving
        # plan (memoized solve returns the hot-swapped executable) and
        # run forward uninterrupted
        r = ep["restored_step"]
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        c_state, _ = fresh_state_and_batch()
        c_state = mgr.restore(c_state, step=r)
        c_step = solve(survivors)
        for i in range(r + 1, 6):
            c_state, c_loss = c_step(c_state, batch)
            assert np.array_equal(losses[i], np.asarray(c_loss)), (
                f"post-resume loss diverged at step {i}: "
                f"{losses[i]!r} != {np.asarray(c_loss)!r}")

    def test_preemption_grace_then_wedge(self, tmp_path):
        """One supervisor, two episodes: a preemption notice whose
        snapshot lands inside the grace window, then a mid-step wedge
        (hung probe) that resets and resumes from the last verified
        checkpoint.  Bitwise continuity must survive both."""
        alpa_tpu.init(cluster="local")
        solve = make_solve()
        state, batch = fresh_state_and_batch()
        det = WedgeDetector(mesh_group=[object()],
                            probe=lambda m: time.sleep(5.0),
                            probe_timeout_s=0.1)
        sup = ElasticSupervisor(solve, state,
                                checkpoint_root=str(tmp_path),
                                wedge_detector=det)
        with fault.FaultPlan(
                fault.FaultSpec("preemption_notice", after=1,
                                exc=lambda: PreemptionNotice(
                                    grace_s=30.0)),
                fault.FaultSpec("stage_launch", times=1, after=20)):
            losses = run_supervised(sup, batch, until=4)

        reasons = [e["reason"] for e in sup.episodes]
        assert reasons == ["preemption_notice", "wedge_detected"], reasons
        preempt, wedge = sup.episodes
        assert preempt["snapshot"] == "grace"
        assert preempt["snapshot_before_kill"] is True
        assert wedge["mid_step"] is True
        assert wedge["snapshot"] == "skipped"  # torn state: never saved
        assert wedge["restored_step"] is not None

        # continuity: same plan throughout, so the loss curve must
        # bitwise-match an uninterrupted run of the same executable
        base_state, _ = fresh_state_and_batch()
        base_step = solve(list(jax.devices()))
        for i in range(1, 5):
            base_state, bl = base_step(base_state, batch)
            assert np.array_equal(losses[i], np.asarray(bl)), i

    def test_new_finding_rejects_candidate_and_rolls_back(self, tmp_path):
        """Pinned negative test: a re-lowered plan whose verdict shows
        ANY new (analysis, code) finding is rejected; the supervisor
        keeps the old plan + devices and training continues bitwise."""
        from alpa_tpu.analysis.plan_verifier import Finding
        from alpa_tpu.pipeline_parallel.pipeshard_executable import \
            PipeshardDriverExecutable

        alpa_tpu.init(cluster="local")
        solve = make_solve()
        state, batch = fresh_state_and_batch()
        sup = ElasticSupervisor(solve, state,
                                checkpoint_root=str(tmp_path))
        run_supervised(sup, batch, until=2)  # captures the baseline
        assert sup._baseline_findings is not None

        orig = PipeshardDriverExecutable.get_plan_verdict

        def tainted(self, mode="registers"):
            v = orig(self, mode)
            if v is not None and not any(
                    f.code == "injected.synthetic" for f in v.warnings):
                v.warnings.append(Finding(
                    "injected", "injected.synthetic",
                    "pretend regression on the candidate plan"))
            return v

        PipeshardDriverExecutable.get_plan_verdict = tainted
        try:
            survivors = list(jax.devices())[4:]
            with fault.FaultPlan(fault.FaultSpec(
                    "worker_lost", times=1,
                    exc=lambda: WorkerLost(survivors=survivors))):
                losses = run_supervised(sup, batch, until=4)
        finally:
            PipeshardDriverExecutable.get_plan_verdict = orig

        ep = sup.episodes[0]
        assert ep["replan"] == "rejected"
        # rollback: old plan, old devices
        assert len(sup.devices) == 8
        assert sup._step_fn is solve(list(jax.devices()))

        base_state, _ = fresh_state_and_batch()
        base_step = solve(list(jax.devices()))
        for i in range(1, 5):
            base_state, bl = base_step(base_state, batch)
            if i in losses:
                assert np.array_equal(losses[i], np.asarray(bl)), i


class TestCkptToolLastGood:
    """Satellite 2: the supervisor and the shell runbook share one
    source of truth for the restore target."""

    def test_prints_last_verified_step(self, tmp_path):
        state, _ = create_mlp_train_state_and_batch(8, hidden_dim=8)
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(3, state, sync=True)
        mgr.save(7, state, sync=True)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                          "ckpt_tool.py"),
             "last-good", str(tmp_path)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "7"
        assert int(out.stdout) == mgr.last_verified_step()

    def test_skips_corrupt_newest_step(self, tmp_path):
        state, _ = create_mlp_train_state_and_batch(8, hidden_dim=8)
        mgr = CheckpointManager(str(tmp_path), async_save=False)
        mgr.save(3, state, sync=True)
        state7 = state.replace(params=jax.tree_util.tree_map(
            lambda x: x + 1, state.params))
        mgr.save(7, state7, sync=True)
        # bit-rot a chunk only step 7 references (the store is
        # content-addressed: identical leaves dedupe across steps)
        step3_hashes = {e["hash"]
                        for l in mgr.store.read_manifest(3)["leaves"]
                        .values() for e in l["chunks"]}
        manifest = mgr.store.read_manifest(7)
        only7 = [e["hash"] for l in manifest["leaves"].values()
                 for e in l["chunks"] if e["hash"] not in step3_hashes]
        assert only7, "step 7 shares every chunk with step 3?"
        path = mgr.store.chunk_path(only7[0])
        with open(path, "r+b") as f:
            f.write(b"\xff" * 8)
        out = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                          "ckpt_tool.py"),
             "last-good", str(tmp_path)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "3"

    def test_exits_nonzero_when_nothing_verifies(self, tmp_path):
        (tmp_path / "manifests").mkdir()
        out = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "scripts",
                                          "ckpt_tool.py"),
             "last-good", str(tmp_path)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode != 0
