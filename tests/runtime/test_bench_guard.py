"""Chip-protection discipline in bench.py (VERDICT r2 next #1).

The estimator must separate the observed-good config from every config
that has wedged the relay, and the recovery loop must always emit one
JSON line.
"""
import dataclasses
import io
import json
import sys

import jax.numpy as jnp

import bench
from alpa_tpu.model.gpt_model import GPTConfig

GOOD = GPTConfig(hidden_size=2048, num_layers=16, num_heads=32, seq_len=1024,
                 vocab_size=51200, dtype=jnp.bfloat16, remat_blocks=True)


def test_hbm_gate_separates_good_from_wedging_configs():
    good = bench.estimate_hbm_gb(GOOD, 8)
    assert good < bench.HBM_GATE_GB
    # every historically wedging config must be refused
    dots = bench.estimate_hbm_gb(
        dataclasses.replace(GOOD, remat_policy="dots"), 8)
    bs16 = bench.estimate_hbm_gb(GOOD, 16)
    l24_fp32 = bench.estimate_hbm_gb(
        dataclasses.replace(GOOD, num_layers=24), 8)
    no_remat = bench.estimate_hbm_gb(
        dataclasses.replace(GOOD, remat_blocks=False), 8)
    for est in (dots, bs16, l24_fp32, no_remat):
        assert est > bench.HBM_GATE_GB
    assert no_remat > good  # dropping remat must not look cheaper
    # the growth path stays open: l24 with bf16 moments + chunked CE
    # fits — 6.0 B/p is exactly what ALPA_TPU_BENCH_OPT=bf16adam ships
    # (only mu in bf16), so this asserts the real runtime variant
    l24_lean = bench.estimate_hbm_gb(
        dataclasses.replace(GOOD, num_layers=24), 8,
        optimizer_bytes_per_param=6.0, chunked_ce=True)
    assert l24_lean < bench.HBM_GATE_GB


class _FakeClock:
    def __init__(self):
        self.now = 0.0

    def time(self):
        return self.now

    def sleep(self, s):
        self.now += s


def _capture_recovery(monkeypatch, probe_results, inner_line, budget=400.0):
    clock = _FakeClock()
    probes = iter(probe_results)

    def probe():
        clock.now += 5.0  # a probe costs wall-clock even when mocked
        return next(probes, False)

    monkeypatch.setattr(bench, "_probe_once", probe)
    monkeypatch.setattr(
        bench, "_run_inner",
        lambda timeout: (inner_line, None if inner_line else "rc=1: boom"))
    monkeypatch.setattr(bench.time, "sleep", clock.sleep)
    monkeypatch.setattr(bench.time, "time", clock.time)
    out = io.StringIO()
    monkeypatch.setattr(sys, "stdout", out)
    rc = bench._run_with_recovery(budget)
    return rc, out.getvalue()


def test_recovery_emits_result_after_wedge_clears(monkeypatch):
    line = json.dumps({"metric": "gpt_train_tflops_per_chip", "value": 76.0})
    rc, out = _capture_recovery(monkeypatch, [False, False, True], line)
    assert rc == 0
    assert json.loads(out.strip())["value"] == 76.0


def test_recovery_emits_zero_line_when_never_clears(monkeypatch):
    rc, out = _capture_recovery(monkeypatch, [False] * 100, None)
    assert rc == 1
    rec = json.loads(out.strip())
    assert rec["value"] == 0.0
    assert "probe_history" in rec["detail"]


def test_recovery_stops_on_deterministic_child_failure(monkeypatch):
    # probe always ok, child always fails fast with rc=1: must stop after
    # MAX_CHILD_FAILURES, not hammer the chip for the whole budget
    rc, out = _capture_recovery(monkeypatch, [True] * 100, None, budget=3600)
    assert rc == 1
    rec = json.loads(out.strip())
    assert rec["detail"]["error"] == "bench child kept failing"
    assert len([p for p in rec["detail"]["probe_history"] if p == "ok"]) \
        <= bench.MAX_CHILD_FAILURES


def test_gate_refusal_returns_nonzero(monkeypatch):
    refusal = json.dumps({"metric": "gpt_train_tflops_per_chip",
                          "value": 0.0, "unit": "TFLOPS/chip",
                          "vs_baseline": 0.0,
                          "detail": {"error": "refused: estimated 20 GB"}})
    rc, out = _capture_recovery(monkeypatch, [True], refusal)
    assert rc == 1
    assert json.loads(out.strip())["detail"]["error"].startswith("refused")
