"""Schedule unit tests (ref tests/pipeline_parallel/test_schedules.py)."""
import numpy as np
import pytest

from alpa_tpu.pipeline_parallel.schedules import (
    GpipeSchedule, InferenceSchedule, OverlapFriendlyPipeDreamSchedule,
    PipeDreamFlush, create_pipeline_schedule)


def _check_complete(sched, num_meshes, num_batch, has_backward=True):
    """Every (mb, stage) task appears exactly once, dependencies hold."""
    seen = {}
    fwd_clock = {}
    bwd_clock = {}
    for k, tick in enumerate(sched.schedules):
        assert len(tick) == num_meshes
        for d, task in enumerate(tick):
            if task is None:
                continue
            assert task not in seen, f"duplicate task {task}"
            seen[task] = k
            mb, s = task
            if s < num_meshes:
                fwd_clock[(mb, s)] = k
            else:
                bwd_clock[(mb, 2 * num_meshes - 1 - s)] = k
    expected = num_meshes * num_batch * (2 if has_backward else 1)
    assert len(seen) == expected, f"{len(seen)} != {expected}"
    # forward deps: F(mb, s) after F(mb, s-1)
    for (mb, s), k in fwd_clock.items():
        if s > 0:
            assert fwd_clock[(mb, s - 1)] < k
    for (mb, d), k in bwd_clock.items():
        assert fwd_clock[(mb, d)] < k
        if d < num_meshes - 1:
            assert bwd_clock[(mb, d + 1)] < k


class TestSchedules:

    @pytest.mark.parametrize("m,n", [(2, 2), (2, 4), (4, 8), (3, 5)])
    def test_gpipe_complete(self, m, n):
        s = GpipeSchedule(num_stages=2 * m, num_meshes=m, num_batch=n)
        _check_complete(s, m, n)

    @pytest.mark.parametrize("m,n", [(2, 2), (2, 4), (4, 8), (3, 5), (4, 2)])
    def test_1f1b_complete(self, m, n):
        s = PipeDreamFlush(num_stages=2 * m, num_meshes=m, num_batch=n)
        _check_complete(s, m, n)

    def test_1f1b_memory_bound(self):
        """1F1B: mesh 0 holds at most m in-flight forward microbatches."""
        m, n = 4, 16
        s = PipeDreamFlush(num_stages=2 * m, num_meshes=m, num_batch=n)
        in_flight = 0
        max_in_flight = 0
        for tick in s.schedules:
            t = tick[0]
            if t is not None:
                if t[1] == 0:
                    in_flight += 1
                else:
                    in_flight -= 1
                max_in_flight = max(max_in_flight, in_flight)
        assert max_in_flight <= m, max_in_flight

    @pytest.mark.parametrize("m,n", [(2, 4), (4, 8), (3, 5)])
    def test_overlap_friendly_complete(self, m, n):
        s = OverlapFriendlyPipeDreamSchedule(num_stages=2 * m, num_meshes=m,
                                             num_batch=n)
        _check_complete(s, m, n)

    def test_overlap_friendly_deeper_warmup(self):
        """Mesh 0 runs more forwards before its first backward than plain
        1F1B (the eager-forward overlap window, ref schedules.py:452)."""
        m, n = 4, 16

        def warmup_len(sched):
            count = 0
            for tick in sched.schedules:
                t = tick[0]
                if t is None:
                    continue
                if t[1] == 0:
                    count += 1
                else:
                    return count
            return count

        plain = PipeDreamFlush(num_stages=2 * m, num_meshes=m, num_batch=n)
        overlap = OverlapFriendlyPipeDreamSchedule(num_stages=2 * m,
                                                   num_meshes=m, num_batch=n)
        assert warmup_len(plain) == m  # m-1 warmup + 1 steady fwd
        assert warmup_len(overlap) == 2 * m  # 2m-1 warmup + 1 steady fwd

    def test_inference(self):
        s = InferenceSchedule(num_stages=3, num_meshes=3, num_batch=4)
        _check_complete(s, 3, 4, has_backward=False)

    def test_factory(self):
        for name in ("gpipe", "1f1b", "1f1b_overlap_friendly", "inference"):
            s = create_pipeline_schedule(name, num_stages=4, num_meshes=2,
                                         num_batch=2)
            assert s.num_clock > 0
        with pytest.raises(ValueError):
            create_pipeline_schedule("bogus", num_stages=4, num_meshes=2,
                                     num_batch=2)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
