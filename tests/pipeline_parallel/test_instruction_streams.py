"""Per-mesh instruction streams (VERDICT r2 missing#3 / weak#6).

The emitter pre-partitions the global instruction list into per-mesh
worker streams with cross-stream dependency edges — the single-controller
analog of the reference's pre-pushed per-worker instruction lists (ref
runtime_emitter.py:258, pipeshard_executable.py:489) — and the driver
executes them on worker threads in single-process mode.
"""
import jax

import alpa_tpu
from alpa_tpu import PipeshardParallel
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.layer_construction import ManualLayerOption
from alpa_tpu.pipeline_parallel.runtime_emitter import (
    PipelineInstType, PipelineInstruction, partition_streams)
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import (assert_allclose,
                              create_mlp_train_state_and_batch,
                              get_mlp_train_step)


def _run(stage, mb, mesh, ins, outs, donate=()):
    class _FakeExec:  # noqa: D401 - minimal donate_idx carrier
        donate_idx = tuple(donate)

    inst = PipelineInstruction(PipelineInstType.RUN, stage_id=stage,
                               micro_batch=mb, dst_mesh=mesh,
                               input_keys=list(ins), output_keys=list(outs))
    inst.executable = _FakeExec()
    return inst


class TestPartitionStreams:

    def test_raw_dependency_across_streams(self):
        """Consumer on mesh 1 must wait for the producer on mesh 0 via the
        RESHARD that carries the value across."""
        insts = [
            _run(0, 0, 0, [("x", 0)], [("a", 0)]),
            PipelineInstruction(PipelineInstType.RESHARD, var_key=("a", 0),
                                src_mesh=0, dst_mesh=1, dst_sharding=None),
            _run(1, 0, 1, [("a", 0)], [("b", 0)]),
        ]
        st = partition_streams(insts, 2)
        assert st.streams[0] == [0]
        assert st.streams[1] == [1, 2]
        # the RESHARD (idx 1, stream 1) reads mesh-0's ("a",0): RAW on 0
        assert st.deps[1] == {0}
        # the consumer RUN (idx 2) reads ("a",0) on mesh 1, written by the
        # RESHARD in its own stream -> no cross-stream dep
        assert 2 not in st.deps

    def test_anti_dependency_for_donation_and_free(self):
        """A RUN that donates a buffer, and a FREE, must wait for every
        earlier reader in other streams."""
        insts = [
            _run(0, 0, 0, [("p", -1)], [("a", 0)]),          # reads p@0
            PipelineInstruction(PipelineInstType.RESHARD, var_key=("p", -1),
                                src_mesh=0, dst_mesh=1, dst_sharding=None),
            # donates p@0 while stream 1's RESHARD also reads p@0
            _run(1, 1, 0, [("p", -1)], [("c", 1)], donate=(0,)),
        ]
        st = partition_streams(insts, 2)
        assert st.deps[2] == {1}, st.deps
        # FREE follows its last user's stream and waits for other readers
        insts.append(PipelineInstruction(PipelineInstType.FREE,
                                         free_keys=[("a", 0, 0)]))
        st = partition_streams(insts, 2)
        assert st.stream_of[3] == st.stream_of[2]

    def test_all_edges_point_backward(self):
        """No dependency edge may point forward in global order (the
        deadlock-freedom invariant)."""
        insts = [
            _run(0, mb, mb % 3, [("x", mb)], [(f"y{mb}", mb)])
            for mb in range(9)
        ]
        insts.insert(4, PipelineInstruction(
            PipelineInstType.RESHARD, var_key=("y0", 0), src_mesh=0,
            dst_mesh=2, dst_sharding=None))
        st = partition_streams(insts, 3)
        for i, deps in st.deps.items():
            assert all(d < i for d in deps)
            assert all(st.stream_of[d] != st.stream_of[i] for d in deps)


class TestDispatchRaceChecker:

    def _insts(self):
        return [
            _run(0, 0, 0, [("x", 0)], [("a", 0)]),
            _run(1, 0, 1, [("a", 0)], [("b", 0)]),
        ]

    def test_detects_cross_stream_conflict(self):
        """Concurrent write/read of one key from different streams is a
        violation (simulating a missing dependency edge)."""
        from alpa_tpu.pipeline_parallel.runtime_emitter import (
            DispatchRaceChecker)
        insts = self._insts()
        # both instructions touch ("a", 0) on mesh... make them conflict:
        # inst 0 writes (a,0)@0; craft inst 1 to read (a,0)@0 from
        # stream 1 (as if a RESHARD pulled from mesh 0)
        insts[1] = PipelineInstruction(
            PipelineInstType.RESHARD, var_key=("a", 0), src_mesh=0,
            dst_mesh=1, dst_sharding=None)
        chk = DispatchRaceChecker(insts, {0: 0, 1: 1})
        a0 = chk.begin(0)           # write in flight on stream 0
        chk.begin(1)                # concurrent cross-stream read
        assert chk.violations, "expected a write/read race"
        chk.end(0, a0)
        import pytest as _pytest
        with _pytest.raises(RuntimeError, match="raced"):
            chk.check()

    def test_serialized_accesses_are_clean(self):
        from alpa_tpu.pipeline_parallel.runtime_emitter import (
            DispatchRaceChecker)
        insts = self._insts()
        chk = DispatchRaceChecker(insts, {0: 0, 1: 1})
        a0 = chk.begin(0)
        chk.end(0, a0)
        a1 = chk.begin(1)           # after the writer finished: fine
        chk.end(1, a1)
        assert not chk.violations
        chk.check()

    def test_reads_do_not_conflict(self):
        from alpa_tpu.pipeline_parallel.runtime_emitter import (
            DispatchRaceChecker)
        insts = [
            _run(0, 0, 0, [("x", 0)], [("a", 0)]),
            _run(1, 0, 1, [("x", 0)], [("b", 0)]),
        ]
        # same key read concurrently from two streams: no violation...
        # except the keys differ by mesh here, so craft same-mesh reads
        insts[1] = _run(1, 0, 0, [("x", 0)], [("b", 0)])
        chk = DispatchRaceChecker(insts, {0: 0, 1: 1})
        a0 = chk.begin(0)
        a1 = chk.begin(1)
        # ("x",0)@0 read concurrently: fine; the writes target different
        # keys ("a" vs "b")
        assert not chk.violations
        chk.end(0, a0)
        chk.end(1, a1)

    def test_end_to_end_clean_under_detector(self):
        """A full threaded pipeshard run under the detector reports no
        violations — the partitioner's edges serialize every conflict."""
        alpa_tpu.init(cluster="local")
        global_config.debug_dispatch_races = True
        global_config.pipeline_dispatch_mode = "threaded"
        try:
            state, batch = create_mlp_train_state_and_batch(
                batch_size=64, num_layers=4, manual_pipeline_layer=True)
            method = PipeshardParallel(
                num_micro_batches=4,
                layer_option=ManualLayerOption(),
                stage_option=UniformStageOption(num_stages=2))
            step = get_mlp_train_step(method, use_value_and_grad=True)
            for _ in range(3):
                state, loss = step(state, batch)
            import math
            assert math.isfinite(float(loss))
            ex = step.get_last_executable()
            # the detector only certifies anything if threads actually ran
            assert ex.last_dispatch_stats["mode"] == "threaded"
        finally:
            global_config.debug_dispatch_races = False
            global_config.pipeline_dispatch_mode = "auto"


class TestThreadedDispatch:

    def test_threaded_matches_sequential(self):
        """Identical numerics under both dispatch modes, and the stats
        record which mode ran."""
        alpa_tpu.init(cluster="local")
        results = {}
        for mode in ("sequential", "threaded"):
            global_config.pipeline_dispatch_mode = mode
            try:
                state, batch = create_mlp_train_state_and_batch(
                    batch_size=64, num_layers=4, manual_pipeline_layer=True)
                method = PipeshardParallel(
                    num_micro_batches=2,
                    layer_option=ManualLayerOption(),
                    stage_option=UniformStageOption(num_stages=2))
                step = get_mlp_train_step(method, use_value_and_grad=True)
                for _ in range(2):
                    state, loss = step(state, batch)
                ex = step.get_last_executable()
                assert ex.last_dispatch_stats["mode"] == mode
                st = ex._instruction_streams
                assert sum(len(s) for s in st.streams) == \
                    len(ex.instructions)
                results[mode] = (float(loss),
                                 jax.device_get(state.params))
            finally:
                global_config.pipeline_dispatch_mode = "auto"
        assert_allclose(results["sequential"][0], results["threaded"][0],
                        1e-6, 1e-6)
        assert_allclose(results["sequential"][1], results["threaded"][1],
                        1e-6, 1e-6)


if __name__ == "__main__":
    import pytest
    pytest.main([__file__, "-x", "-q"])
