"""Explicit-state plan model checker (ISSUE 13 tentpole).

Oracle 1: the committed 2-mesh fixture plan is proven deadlock- and
hazard-free under BOTH channel semantics (buffered and rendezvous)
within the state budget, with the partial-order reduction ratio
reported and every ``fault.KNOWN_SITES`` site classified.  Oracle 2: a
pinned plan that passes the Kahn-based deadlock analysis is rejected
by the model checker with a rendered counterexample schedule (FIFO
channel reorder — invisible to the happens-before DAG).  Oracle 3:
seeded plan mutations (dropped FREE, swapped cross-stream RESHARD
pair, corrupted channel edge, shrunken in-flight window) are each
caught by a named finding.  Oracle 4: the classification feeds
``fault.call_with_retry`` — under verify_plans="error" a statically
unsafe site refuses real-error retries while injected faults stay
retryable.  Oracle 5: a live 2-mesh lowering is model-checked end to
end in fixture mode (the default) within the wall-clock budget, and
the perf gate pins the fixture's exact state count.
"""
import dataclasses
import json
import os
import random
import subprocess
import sys

import pytest

import alpa_tpu
from alpa_tpu import PipeshardParallel
from alpa_tpu.analysis import model_check as mc
from alpa_tpu.analysis import plan_verifier as pv
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
from alpa_tpu.pipeline_parallel.runtime_emitter import OpHook
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
FIXTURE = os.path.join(REPO, "benchmark", "results",
                       "model_check_fixture_plan.json")


@pytest.fixture(autouse=True)
def _restore_globals():
    prev_mode = global_config.pipeline_dispatch_mode
    prev_verify = global_config.verify_plans
    prev_mc = global_config.verify_plans_model_check
    prev_dir = global_config.compile_cache_dir
    yield
    global_config.pipeline_dispatch_mode = prev_mode
    global_config.verify_plans = prev_verify
    global_config.verify_plans_model_check = prev_mc
    global_config.compile_cache_dir = prev_dir
    from alpa_tpu import fault
    fault.install_retry_classification(None)
    from alpa_tpu.compile_cache import reset_compile_cache
    reset_compile_cache()


def _compile_pipeline(num_stages=2, mode="registers"):
    alpa_tpu.init("local")
    global_config.pipeline_dispatch_mode = mode
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=4),
        stage_option=UniformStageOption(num_stages=num_stages))
    step = get_mlp_train_step(method, use_value_and_grad=False)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)
    state, _ = step(state, batch)
    return step.get_last_executable(), state, batch, step


def _codes(findings):
    return {f.code for f in findings}


# ---------------------------------------------------------------------
# oracle 1: the committed fixture is proven clean under both semantics
# ---------------------------------------------------------------------

def test_committed_fixture_passes_both_semantics():
    model, hooks, window = mc.load_fixture(FIXTURE)
    result = mc.check_model(model, hooks=hooks, overlap_window=window)
    st = result.stats
    assert st["semantics"] == {"buffered": "pass", "rendezvous": "pass"}
    assert not st["partial"], "fixture must fit the default budget"
    assert result.ok, result.format()
    # exhaustive exploration actually happened, and POR actually reduced
    assert st["states"] > 0 and st["transitions"] > 0
    assert st["por_commits"] > 0
    assert 0.0 < st["reduction_ratio"] < 1.0, st
    assert st["counterexample"] is None
    # the declared overlap window is honored as a model property
    assert st["declared_window"] == window == 2
    assert st["max_inflight"] == 2
    # every registered fault site gets a classification
    from alpa_tpu import fault
    sites = st["retry_sites"]
    assert set(sites) == set(fault.KNOWN_SITES)
    for ent in sites.values():
        assert ent["classification"] in ("safe", "unsafe", "unreachable")
    assert sites["stage_launch"]["classification"] == "unsafe"
    assert "unsafe-donation" in sites["stage_launch"]["reasons"]
    assert sites["cross_mesh_send"]["classification"] == "unsafe"
    assert "fifo-reorder" in sites["cross_mesh_send"]["reasons"]
    assert sites["probe"]["classification"] == "unreachable"
    # retry findings are descriptive notes, not errors
    assert _codes(result.findings) == {"retry.unsafe-donation",
                                       "retry.fifo-reorder"}
    # the human-readable report carries the headline numbers
    text = result.format()
    assert "buffered=pass" in text and "rendezvous=pass" in text
    assert "reduction_ratio" in text and "retry sites" in text


def test_idempotent_plan_classifies_site_safe():
    """A plan whose only hooked ops are idempotent singletons with no
    channel overlap gets its site classified ``safe``."""
    model, _hooks, _w = mc.load_fixture(FIXTURE)
    hooks = [OpHook("exec", "RUN stage1", 1, 0, writes=(3,),
                    slots=(3,), fault_site="stage_launch",
                    members=(1,))]
    _findings, sites = mc.classify_retry_sites(model, hooks)
    assert sites["stage_launch"] == {"classification": "safe",
                                     "reasons": [], "hooks": 1}


def test_budget_exhaustion_is_partial_never_a_false_verdict():
    model, hooks, window = mc.load_fixture(FIXTURE)
    result = mc.check_model(model, hooks=hooks, overlap_window=window,
                            budget=3)
    assert result.stats["partial"] is True
    assert "model.budget-exhausted" in _codes(result.findings)
    # budget exhaustion alone is a note: no error-severity finding
    assert result.ok, result.format()
    assert "partial" in set(result.stats["semantics"].values())


# ---------------------------------------------------------------------
# oracle 2: Kahn passes, the model checker catches the FIFO deadlock
# ---------------------------------------------------------------------

_F32 = "float32"


def _slot(s, var, mesh, **kw):
    return pv.SlotModel(s, var, 0, mesh, (4, 4), _F32, 64, 64, **kw)


def _kahn_blind_deadlock_model():
    """One producer RUN writes two payloads; both RESHARD onto the same
    (0, 1) channel; the destination stream receives them in the
    OPPOSITE order of the sends.  The happens-before DAG is acyclic
    (each RECV waits only on the producer), so Kahn's algorithm — and
    the production-order channel heuristic, which sees identical
    producer positions — pass; but a FIFO channel delivers op1's
    payload first, which receiver-first-op2 can never accept."""
    slots = {0: _slot(0, "x", 0, preplaced=True),
             1: _slot(1, "a", 0), 2: _slot(2, "b", 0),
             3: _slot(3, "a'", 1), 4: _slot(4, "b'", 1),
             5: _slot(5, "y", 1, protected=True)}
    ops = [
        pv.OpModel(0, "RUN", 0, reads=(0,), writes=(1, 2),
                   label="RUN produce"),
        pv.OpModel(1, "RESHARD", 1, reads=(1,), writes=(3,),
                   edge=(0, 1), cross=True, nbytes=64,
                   label="RESHARD a 0->1"),
        pv.OpModel(2, "RESHARD", 1, reads=(2,), writes=(4,),
                   edge=(0, 1), cross=True, nbytes=64,
                   label="RESHARD b 0->1"),
        pv.OpModel(3, "RUN", 1, reads=(3, 4), writes=(5,),
                   label="RUN consume"),
    ]
    return pv.PlanModel(ops=ops, slots=slots, num_meshes=2,
                        streams=[[0], [2, 1, 3]],
                        deps={1: {0}, 2: {0}}, mode="registers")


def test_kahn_passes_but_model_checker_catches_fifo_deadlock():
    model = _kahn_blind_deadlock_model()
    # the pre-existing four analyses accept this plan...
    verdict = pv.verify_model(model)
    assert verdict.ok, verdict.format_table()
    assert not any(c.startswith("deadlock.") for c in
                   _codes(verdict.findings())), verdict.format_table()
    # ...the model checker rejects it under BOTH semantics
    result = mc.check_model(model)
    assert result.stats["semantics"]["buffered"] == "deadlock"
    assert result.stats["semantics"]["rendezvous"] == "deadlock"
    assert "model.deadlock" in _codes(result.findings)
    assert mc.severity_of("model.deadlock") == "error"
    # the counterexample is a rendered instruction schedule naming the
    # blocked receive and the channel state that blocks it
    trace = result.stats["counterexample"]
    assert trace, result.format()
    text = result.format()
    assert "counterexample" in text
    assert "FIFO head" in text, text
    # merged through verify_model the finding is an error -> not ok
    verdict = pv.verify_model(model, model_check=True)
    assert not verdict.ok
    assert "model.deadlock" in _codes(verdict.errors)
    assert verdict.stats["model_check"]["counterexample"]


def test_rendezvous_only_deadlock_is_a_warning():
    """Clean under buffered channels, deadlocked under rendezvous: the
    plan silently relies on staging memory — reported as a warning."""
    slots = {0: _slot(0, "x", 0, preplaced=True),
             1: _slot(1, "a", 0), 2: _slot(2, "b", 0),
             3: _slot(3, "a'", 1), 4: _slot(4, "b'", 1),
             5: _slot(5, "w", 0), 6: _slot(6, "y", 1, protected=True)}
    ops = [
        pv.OpModel(0, "RUN", 0, reads=(0,), writes=(1, 2),
                   label="RUN produce"),
        pv.OpModel(1, "RESHARD", 1, reads=(1,), writes=(3,),
                   edge=(0, 1), cross=True, label="RESHARD a 0->1"),
        pv.OpModel(2, "RESHARD", 1, reads=(2,), writes=(4,),
                   edge=(0, 1), cross=True, label="RESHARD b 0->1"),
        pv.OpModel(3, "RUN", 0, writes=(5,), label="RUN x"),
        pv.OpModel(4, "RUN", 1, writes=(), label="RUN w"),
        pv.OpModel(5, "RUN", 1, reads=(3, 4), writes=(6,),
                   label="RUN consume"),
    ]
    model = pv.PlanModel(ops=ops, slots=slots, num_meshes=2,
                         streams=[[0, 3], [4, 1, 2, 5]],
                         deps={1: {0}, 2: {0}, 4: {3}},
                         mode="registers")
    result = mc.check_model(model)
    assert result.stats["semantics"]["buffered"] == "pass"
    assert result.stats["semantics"]["rendezvous"] == "deadlock"
    assert "model.rendezvous-deadlock" in _codes(result.findings)
    assert result.ok, "rendezvous-only deadlock must not be an error"
    verdict = pv.verify_model(model, model_check=True)
    assert verdict.ok
    assert "model.rendezvous-deadlock" in _codes(verdict.warnings)


# ---------------------------------------------------------------------
# oracle 3: seeded mutation fuzz on the committed fixture
# ---------------------------------------------------------------------

def _mutate_drop_free(model, hooks, window, rng):
    idx = rng.choice([i for i, op in enumerate(model.ops)
                      if op.kind == "FREE"])
    model.ops[idx] = dataclasses.replace(model.ops[idx], kills=())
    return model, hooks, window, "liveness.leak"


def _mutate_swap_recv_pair(model, hooks, window, rng):
    dst = list(model.streams[1])
    i, j = dst.index(2), dst.index(3)
    dst[i], dst[j] = dst[j], dst[i]
    model.streams[1] = dst
    return model, hooks, window, "model.deadlock"


def _mutate_corrupt_channel_edge(model, hooks, window, rng):
    idx = rng.choice([i for i, op in enumerate(model.ops)
                      if op.kind == "RESHARD"])
    model.ops[idx] = dataclasses.replace(model.ops[idx], edge=(1, 0))
    return model, hooks, window, "model.channel-endpoint"


def _mutate_shrink_window(model, hooks, window, rng):
    return model, hooks, 1, "model.inflight-exceeds-window"


_MUTATIONS = [_mutate_drop_free, _mutate_swap_recv_pair,
              _mutate_corrupt_channel_edge, _mutate_shrink_window]


def test_seeded_mutation_fuzz_every_class_is_caught():
    """Each mutation class, applied with rng-chosen targets, must be
    named by SOME analysis — the deterministic seed keeps failures
    reproducible."""
    rng = random.Random(0)
    seen = set()
    for round_no in range(12):
        mutate = rng.choice(_MUTATIONS)
        model, hooks, window = mc.load_fixture(FIXTURE)
        model, hooks, window, expected = mutate(model, hooks, window,
                                               rng)
        verdict = pv.verify_model(model, hooks=hooks, model_check=True,
                                  overlap_window=window)
        assert expected in _codes(verdict.findings()), (
            f"round {round_no}: mutation {mutate.__name__} not caught;"
            f"\n{verdict.format_table()}")
        seen.add(mutate.__name__)
    assert len(seen) == len(_MUTATIONS), (
        f"seed must exercise every mutation class, got {seen}")


# ---------------------------------------------------------------------
# oracle 4: static retry classification gates call_with_retry
# ---------------------------------------------------------------------

def test_statically_unsafe_site_refuses_real_error_retries():
    from alpa_tpu import fault
    policy = fault.RetryPolicy(max_attempts=3, base_delay=0.0,
                               max_delay=0.0, jitter=0.0)
    fault.install_retry_classification(
        {"stage_launch": {"classification": "unsafe",
                          "reasons": ["unsafe-donation"], "hooks": 1}})
    try:
        # under verify_plans=error the static proof wins: one attempt
        global_config.verify_plans = "error"
        attempts = []

        def boom():
            attempts.append(1)
            raise ValueError("real failure")

        with pytest.raises(ValueError):
            fault.call_with_retry(boom, policy=policy,
                                  site="stage_launch", idempotent=True)
        assert len(attempts) == 1, "retry must be refused"

        # injected faults remain retryable: they fire BEFORE the op
        attempts.clear()

        def injected_then_ok():
            attempts.append(1)
            if len(attempts) == 1:
                raise fault.InjectedFault("stage_launch", "injected")
            return "ok"

        assert fault.call_with_retry(
            injected_then_ok, policy=policy, site="stage_launch",
            idempotent=False) == "ok"
        assert len(attempts) == 2

        # under warn the caller's idempotent declaration still rules
        global_config.verify_plans = "warn"
        attempts.clear()
        with pytest.raises(ValueError):
            fault.call_with_retry(boom, policy=policy,
                                  site="stage_launch", idempotent=True)
        assert len(attempts) == 3, "warn mode must retry as declared"
    finally:
        fault.install_retry_classification(None)
    assert fault.get_retry_classification() == {}


# ---------------------------------------------------------------------
# oracle 5: live end-to-end lowering, knob, metrics, dump, CLI, gate
# ---------------------------------------------------------------------

def test_live_two_mesh_lowering_is_model_checked_end_to_end():
    import time
    t0 = time.perf_counter()
    ex, *_ = _compile_pipeline(num_stages=2)
    wall = time.perf_counter() - t0
    prog = ex._register_programs["registers"]
    verdict = prog.verdict
    assert verdict is not None and verdict.ok, verdict.format_table()
    st = verdict.stats.get("model_check")
    assert st, ("fixture mode is the default: a 2-mesh tier-1 plan "
                "must be model-checked")
    assert st["semantics"]["buffered"] == "pass", verdict.format_table()
    assert st["semantics"]["rendezvous"] == "pass"
    assert not st["partial"]
    assert st["n_channels"] >= 1, "2-mesh plan must have a channel"
    assert st["states"] > 0 and 0.0 < st["reduction_ratio"] <= 1.0
    # the walk itself is milliseconds; the whole compile+step stays
    # well inside the tier-1 wall-clock budget
    assert st["seconds"] < 5.0, st
    assert wall < 120.0, wall
    # real plans classify their reachable sites (donated apply-grad
    # RUNs make stage_launch unsafe)
    from alpa_tpu import fault
    sites = st["retry_sites"]
    assert set(sites) == set(fault.KNOWN_SITES)
    assert sites["stage_launch"]["classification"] == "unsafe"
    # ...and the classification is installed into fault.py
    assert fault.get_retry_classification()[
        "stage_launch"]["classification"] == "unsafe"
    # counters registered and incremented
    from alpa_tpu.telemetry.metrics import get_registry
    text = get_registry().to_prometheus_text()
    assert "alpa_model_check_states_total" in text
    assert 'alpa_plan_model_check_total{result="ok"}' in text
    # the verdict table renders the model-check line
    assert "model check:" in verdict.format_table()


def test_partition_streams_channel_metadata_and_independence():
    """The stream partitioner reports per-edge FIFO channel membership
    in send order, and the op-independence predicate agrees with the
    access-conflict oracle on every real instruction pair."""
    import itertools
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        PipelineInstType, instruction_accesses, instructions_independent,
        partition_streams)
    ex, *_ = _compile_pipeline(num_stages=2)
    insts = list(ex.instructions)
    st = partition_streams(insts, 2)
    expected = {}
    for i, inst in enumerate(insts):
        if inst.opcode == PipelineInstType.RESHARD and \
                inst.src_mesh != inst.dst_mesh:
            expected.setdefault(
                (inst.src_mesh, inst.dst_mesh), []).append(i)
    assert expected, "2-mesh plan must cross meshes"
    assert st.channels == expected
    # the model passed to the checker carries the same channel map
    prog = ex._register_programs["registers"]
    assert prog.verdict.stats["model_check"]["n_channels"] == \
        len(expected)
    n_indep = n_conflict = 0
    for a, b in itertools.combinations(insts[:20], 2):
        ind = instructions_independent(a, b)
        assert ind == instructions_independent(b, a), "must be symmetric"
        conflict = any(
            ka != "read" or kb != "read"
            for k1, ka in instruction_accesses(a)
            for k2, kb in instruction_accesses(b) if k1 == k2)
        assert ind == (not conflict), (a, b)
        n_indep += int(ind)
        n_conflict += int(not ind)
    assert n_indep > 0 and n_conflict > 0, (n_indep, n_conflict)


def test_model_check_off_knob_skips_the_analysis():
    global_config.verify_plans_model_check = "off"
    ex, *_ = _compile_pipeline(num_stages=2)
    verdict = ex._register_programs["registers"].verdict
    assert verdict is not None and verdict.ok
    assert "model_check" not in verdict.stats


def test_model_check_text_in_debug_dump(tmp_path):
    from alpa_tpu.monitoring import dump_debug_info
    ex, *_ = _compile_pipeline(num_stages=2)
    dump_debug_info(ex, str(tmp_path))
    path = tmp_path / "model_check.txt"
    assert path.exists()
    text = path.read_text()
    assert "model check: buffered=pass" in text, text
    assert "retry sites" in text


def test_verify_tool_modelcheck_cli_on_committed_fixture():
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "verify_tool.py"),
         "modelcheck", "--json"],
        capture_output=True, text=True, check=False, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    out = json.loads(proc.stdout)
    assert out["schema"] == "alpa-model-check/v1"
    assert out["ok"] is True
    assert out["stats"]["semantics"] == {"buffered": "pass",
                                         "rendezvous": "pass"}
    assert {f["code"] for f in out["findings"]} == {
        "retry.unsafe-donation", "retry.fifo-reorder"}
    assert all(f["severity"] == "note" for f in out["findings"])


def test_perf_gate_pins_fixture_state_count():
    """Exploration is deterministic: the committed baseline pins the
    exact state count (ratio 1.0) and a generous wall-clock cap."""
    from benchmark.perf_gate import gate
    model, hooks, window = mc.load_fixture(FIXTURE)
    result = mc.check_model(model, hooks=hooks, overlap_window=window)
    verdict = gate({"modelcheck.states": float(result.stats["states"]),
                    "modelcheck.seconds": result.stats["seconds"]})
    checked = {c["metric"] for c in verdict["checks"]}
    assert {"modelcheck.states", "modelcheck.seconds"} <= checked
    assert verdict["pass"], verdict


def test_fixture_roundtrip_serialization():
    model, hooks, window = mc.load_fixture(FIXTURE)
    d = mc.model_to_dict(model, hooks=hooks, overlap_window=window)
    assert d["format"] == "alpa-model-check-plan/v1"
    with open(FIXTURE, encoding="utf-8") as f:
        committed = json.load(f)
    # normalize tuples -> lists the way the committed file was written
    assert json.loads(json.dumps(d)) == committed, \
        "fixture round-trip must be lossless"
