"""Collective resharding lowering + quantized transfer codec (ISSUE 7).

Oracle 1: every lossless strategy is bit-exact against the
``direct_p2p`` path — at the executor level (one edge, every eligible
collective) and end-to-end on the unified graph executor (forced
strategy over the 4-stage MLP train step, grouped + donated, registers
and overlap modes).  Oracle 2: the codec's documented error contract,
property-style over seeded shapes.  Oracle 3: strategy selection — the
cost model picks collectives exactly when the link wire model makes
them cheaper, forced-but-ineligible strategies degrade to direct, and
decisions replay from the compile cache."""
import numpy as np
import pytest

import alpa_tpu
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr
from alpa_tpu.pipeline_parallel import reshard_codec as codec


@pytest.fixture(autouse=True)
def _restore_knobs():
    prev = (global_config.reshard_strategy,
            global_config.reshard_quantize,
            global_config.reshard_quantize_min_bytes,
            global_config.resharding_wire_model,
            global_config.resharding_wire_bandwidth,
            global_config.resharding_transfer_latency_s,
            global_config.pipeline_dispatch_mode)
    yield
    (global_config.reshard_strategy,
     global_config.reshard_quantize,
     global_config.reshard_quantize_min_bytes,
     global_config.resharding_wire_model,
     global_config.resharding_wire_bandwidth,
     global_config.resharding_transfer_latency_s,
     global_config.pipeline_dispatch_mode) = prev


def _two_meshes(n_src=4, n_dst=4):
    devs = jax.devices()
    return (Mesh(np.array(devs[:n_src]), ("x",)),
            Mesh(np.array(devs[n_src:n_src + n_dst]), ("x",)))


class _Aval:

    def __init__(self, shape, dtype=np.float32):
        self.shape = shape
        self.dtype = np.dtype(dtype)


# ---------------------------------------------------------------------
# strategy selection (cost model + eligibility + cache)
# ---------------------------------------------------------------------

class TestStrategySelection:

    CASES = {
        "rowshard->replicated": (P("x", None), P()),
        "rowshard->colshard": (P("x", None), P(None, "x")),
        "replicated->rowshard": (P(), P("x", None)),
        "rowshard->rowshard": (P("x", None), P("x", None)),
    }

    def _shardings(self, case):
        src_mesh, dst_mesh = _two_meshes()
        ss, ds = self.CASES[case]
        return NamedSharding(src_mesh, ss), NamedSharding(dst_mesh, ds)

    def test_default_knobs_always_direct(self):
        # latency 0 → all candidates tie → direct wins the tie-break,
        # so the default configuration is byte-identical to before
        for case in self.CASES:
            src, dst = self._shardings(case)
            strat, _, _ = cmr.choose_strategy((8, 8), 4, src, dst)
            assert strat == "direct_p2p", case

    def test_link_model_picks_collectives(self):
        global_config.resharding_wire_model = "link"
        global_config.resharding_transfer_latency_s = 0.002
        expect = {
            "rowshard->replicated": "slice_all_gather",
            "rowshard->colshard": "all_to_all",
            "replicated->rowshard": "direct_p2p",   # already 1 msg/link
            "rowshard->rowshard": "direct_p2p",     # aligned, 1 msg/link
        }
        for case, want in expect.items():
            src, dst = self._shardings(case)
            strat, costs, _ = cmr.choose_strategy((8, 8), 4, src, dst)
            assert strat == want, (case, costs)

    def test_link_stats_pinned_4p4(self):
        # rowshard -> replicated, (8,8) f32: direct sends each 64 B
        # shard to all 4 replicas (4 msgs, 256 B per link, 1024 B
        # total); the scattered landing is a 1:1 aligned move (1 msg,
        # 64 B per link, 256 B total)
        src, dst = self._shardings("rowshard->replicated")
        _, _, opts = cmr.choose_strategy((8, 8), 4, src, dst)
        d = opts["direct_p2p"]["stats"]
        assert (d["max_link_messages"], d["max_link_bytes"],
                d["total_bytes"]) == (4, 256.0, 1024.0)
        s = opts["slice_all_gather"]["stats"]
        assert (s["max_link_messages"], s["max_link_bytes"],
                s["total_bytes"]) == (1, 64.0, 256.0)

    def test_forced_ineligible_falls_back_to_direct(self):
        global_config.reshard_strategy = "all_to_all"
        src, dst = self._shardings("rowshard->replicated")  # repl dst
        strat, _, _ = cmr.choose_strategy((8, 8), 4, src, dst)
        assert strat == "direct_p2p"

    def test_forced_eligible_is_taken(self):
        global_config.reshard_strategy = "slice_all_gather"
        src, dst = self._shardings("rowshard->replicated")
        strat, _, _ = cmr.choose_strategy((8, 8), 4, src, dst)
        assert strat == "slice_all_gather"

    def test_resolve_strategy_replays_from_cache(self):
        global_config.resharding_wire_model = "link"
        global_config.resharding_transfer_latency_s = 0.002
        src, dst = self._shardings("rowshard->replicated")
        s1, c1, hit1 = cmr.resolve_strategy((8, 8), 4, src, dst)
        s2, c2, hit2 = cmr.resolve_strategy((8, 8), 4, src, dst)
        assert not hit1 and hit2
        assert s1 == s2 == "slice_all_gather"
        assert c1 == c2

    def test_cache_key_covers_knobs(self):
        # same edge, different knobs → independent decisions
        src, dst = self._shardings("rowshard->replicated")
        s1, _, _ = cmr.resolve_strategy((8, 8), 4, src, dst)
        global_config.resharding_wire_model = "link"
        global_config.resharding_transfer_latency_s = 0.002
        s2, _, hit2 = cmr.resolve_strategy((8, 8), 4, src, dst)
        assert not hit2
        assert (s1, s2) == ("direct_p2p", "slice_all_gather")

    def test_plan_resharding_carries_strategy(self):
        global_config.resharding_wire_model = "link"
        global_config.resharding_transfer_latency_s = 0.002
        src, dst = self._shardings("rowshard->replicated")
        spec = cmr.plan_resharding((8, 8), 4, src, dst)
        assert spec.strategy == "slice_all_gather"
        assert spec.wire_messages == 1
        assert spec.wire_bytes == 256.0
        assert set(spec.strategy_costs) == set(spec.strategy_stats)
        assert cmr.format_resharding_plan().count("slice_all_gather") > 0


# ---------------------------------------------------------------------
# executor bit-exactness (one edge, every strategy)
# ---------------------------------------------------------------------

class TestExecutorBitExactness:

    def _run(self, case_src, case_dst, strategy):
        src_mesh, dst_mesh = _two_meshes()
        src = NamedSharding(src_mesh, case_src)
        dst = NamedSharding(dst_mesh, case_dst)
        shape = (8, 8)
        x = np.arange(64, dtype=np.float32).reshape(shape) * 0.37 - 11.0
        val = jax.device_put(jnp.asarray(x), src)
        _, _, opts = cmr.choose_strategy(shape, 4, src, dst)
        assert strategy in opts, f"{strategy} ineligible for this edge"
        t = cmr.CollectiveTransfer(_Aval(shape), src, dst, strategy,
                                   opts[strategy]["landing"])
        out = t(val)
        assert out.sharding.is_equivalent_to(dst, 2)
        np.testing.assert_array_equal(np.asarray(out), x)

    def test_slice_all_gather(self):
        self._run(P("x", None), P(), "slice_all_gather")

    def test_all_to_all(self):
        self._run(P("x", None), P(None, "x"), "all_to_all")

    def test_reduce_scatter_gather(self):
        self._run(P(), P(), "reduce_scatter_gather")

    def test_make_transfer_weight_never_quantized(self):
        global_config.reshard_quantize = "int8"
        global_config.reshard_quantize_min_bytes = 1
        src_mesh, dst_mesh = _two_meshes()
        src = NamedSharding(src_mesh, P("x", None))
        dst = NamedSharding(dst_mesh, P())
        t = cmr.make_transfer(_Aval((8, 8)), src, dst, cross=True,
                              weight=True)
        assert not isinstance(t, codec.QuantizedTransfer)
        t2 = cmr.make_transfer(_Aval((8, 8)), src, dst, cross=True,
                               weight=False)
        assert isinstance(t2, codec.QuantizedTransfer)

    def test_make_transfer_same_mesh_stays_direct(self):
        global_config.reshard_strategy = "slice_all_gather"
        src_mesh, _ = _two_meshes()
        sh = NamedSharding(src_mesh, P("x", None))
        t = cmr.make_transfer(_Aval((8, 8)), sh, sh, cross=False)
        assert isinstance(t, cmr.DirectTransfer)

    def test_quantized_transfer_within_bound(self):
        global_config.reshard_quantize_min_bytes = 1
        src_mesh, dst_mesh = _two_meshes()
        src = NamedSharding(src_mesh, P("x", None))
        dst = NamedSharding(dst_mesh, P())
        rng = np.random.default_rng(7)
        x = rng.standard_normal((8, 8)).astype(np.float32) * 5
        val = jax.device_put(jnp.asarray(x), src)
        t = codec.maybe_quantized_transfer(_Aval((8, 8)), src, dst,
                                           "int8")
        assert t is not None
        out = t(val)
        assert out.sharding.is_equivalent_to(dst, 2)
        # whole array is one block: error ≤ amax / 254
        bound = np.abs(x).max() / 250 + 1e-7
        assert np.abs(np.asarray(out) - x).max() <= bound


# ---------------------------------------------------------------------
# codec error contract (seeded, property-style)
# ---------------------------------------------------------------------

def _block_bounds(x, frac):
    """Per-element error bound: ``frac`` of the element's block max."""
    flat = np.ravel(np.asarray(x, dtype=np.float32))
    nb = -(-flat.size // codec.BLOCK)
    blocks = np.pad(flat, (0, nb * codec.BLOCK - flat.size)) \
        .reshape(nb, codec.BLOCK)
    amax = np.abs(blocks).max(axis=1, keepdims=True)
    return (np.broadcast_to(amax * frac, blocks.shape)
            .reshape(-1)[:flat.size])


class TestCodecContract:

    SHAPES = [(515,), (256,), (8, 8), (1000, 3), (7,), (1,), (3, 5, 7)]

    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_int8_error_bound(self, seed):
        rng = np.random.default_rng(seed)
        for shape in self.SHAPES:
            x = (rng.standard_normal(shape) *
                 rng.uniform(0.01, 100)).astype(np.float32)
            q, s = codec.encode(jnp.asarray(x), "int8")
            y = np.asarray(codec.decode(q, s, shape, np.float32, "int8"))
            # documented: ≤ amax_block/254 (1/250 + eps gives slack for
            # the fp32 scale arithmetic)
            bound = _block_bounds(x, 1 / 250) + 1e-7
            err = np.abs(np.ravel(y) - np.ravel(x))
            assert (err <= bound).all(), shape

    @pytest.mark.parametrize("seed", [0, 1])
    def test_fp8_error_bound(self, seed):
        if not codec.have_fp8():
            pytest.skip("no float8_e4m3fn in this jax build")
        rng = np.random.default_rng(seed)
        for shape in self.SHAPES:
            x = (rng.standard_normal(shape) *
                 rng.uniform(0.01, 100)).astype(np.float32)
            q, s = codec.encode(jnp.asarray(x), "fp8")
            y = np.asarray(codec.decode(q, s, shape, np.float32, "fp8"))
            # documented: ≤ 7% of the block max magnitude
            bound = _block_bounds(x, 0.07) + 1e-7
            err = np.abs(np.ravel(y) - np.ravel(x))
            assert (err <= bound).all(), shape

    def test_zeros_bit_exact(self):
        for mode in ("int8",) + (("fp8",) if codec.have_fp8() else ()):
            x = jnp.zeros((300,), jnp.float32)
            q, s = codec.encode(x, mode)
            y = codec.decode(q, s, (300,), np.float32, mode)
            np.testing.assert_array_equal(np.asarray(y),
                                          np.zeros(300, np.float32))

    def test_bf16_roundtrip_bound(self):
        rng = np.random.default_rng(3)
        x = (rng.standard_normal((400,)) * 4).astype(jnp.bfloat16)
        q, s = codec.encode(jnp.asarray(x), "int8")
        y = np.asarray(codec.decode(q, s, (400,), jnp.bfloat16,
                                    "int8")).astype(np.float32)
        xf = np.asarray(x).astype(np.float32)
        # int8 step + one bf16 rounding of the decoded value
        bound = _block_bounds(xf, 1 / 250 + 1 / 128) + 1e-6
        assert (np.abs(y - xf) <= bound).all()

    @pytest.mark.parametrize("seed", [0, 1, 2, 3])
    def test_error_bound_table_is_the_contract(self, seed):
        """ISSUE 14 satellite: the ``codec.ERROR_BOUND`` table — the
        single source of truth the numerics certification composes —
        must hold property-style on adversarial inputs: exact zeros,
        denormals, ragged tails, bf16 payloads."""
        rng = np.random.default_rng(seed)
        modes = ("int8",) + (("fp8",) if codec.have_fp8() else ())
        for mode in modes:
            frac = codec.ERROR_BOUND[mode]
            for shape in [(codec.BLOCK,), (codec.BLOCK + 3,), (5,),
                          (2, codec.BLOCK - 1)]:
                n = int(np.prod(shape))
                x = (rng.standard_normal(n) *
                     rng.uniform(1e-3, 1e3)).astype(np.float32)
                # sprinkle exact zeros and denormals into every block
                x[rng.integers(0, n, size=max(1, n // 7))] = 0.0
                x[rng.integers(0, n, size=max(1, n // 11))] = 1e-40
                x = x.reshape(shape)
                q, s = codec.encode(jnp.asarray(x), mode)
                y = np.asarray(codec.decode(q, s, shape, np.float32,
                                            mode))
                bound = _block_bounds(x, frac) + 1e-7
                err = np.abs(np.ravel(y) - np.ravel(x))
                assert (err <= bound).all(), (mode, shape)
            # bf16 payload: table bound + one bf16 rounding step
            xb = (rng.standard_normal(300) * 2).astype(jnp.bfloat16)
            q, s = codec.encode(jnp.asarray(xb), mode)
            yb = np.asarray(codec.decode(q, s, (300,), jnp.bfloat16,
                                         mode)).astype(np.float32)
            xf = np.asarray(xb).astype(np.float32)
            bound = _block_bounds(xf, frac + 1 / 128) + 1e-6
            assert (np.abs(yb - xf) <= bound).all(), mode

    def test_error_bound_values_pinned(self):
        """The documented bounds the plan verifier composes: int8 is
        blockmax/254 (symmetric int8 over 127 steps), fp8 is 7%."""
        assert codec.ERROR_BOUND["int8"] == 1.0 / 254.0
        assert codec.ERROR_BOUND["fp8"] == 0.07

    def test_eligibility_gating(self):
        global_config.reshard_quantize_min_bytes = 65536
        big, small = _Aval((256, 256)), _Aval((8, 8))
        assert codec.eligible(big, "int8")
        assert not codec.eligible(small, "int8")        # below threshold
        assert not codec.eligible(_Aval((256, 256), np.int32), "int8")
        assert not codec.eligible(_Aval((256, 256), np.float16), "int8")
        assert not codec.eligible(big, "off")
        assert codec.eligible(_Aval((256, 256), jnp.bfloat16), "int8")

    def test_wire_bytes_reduction(self):
        # fp32 → int8 with one fp32 scale per 256 elements: ≥ 3.5x
        n = 1024 * 256
        ratio = (n * 4) / codec.wire_bytes((1024, 256), 4, "int8")
        assert ratio >= 3.5

    def test_passthrough_bit_exact(self):
        """Lossless path sanity: with the codec off (or the edge
        ineligible) a cross-mesh fp32/bf16 transfer is bit-exact."""
        src_mesh, dst_mesh = _two_meshes()
        src = NamedSharding(src_mesh, P("x", None))
        dst = NamedSharding(dst_mesh, P())
        for dtype in (np.float32, jnp.bfloat16):
            x = (np.arange(64).reshape(8, 8) * 0.123).astype(dtype)
            t = cmr.make_transfer(_Aval((8, 8), dtype), src, dst,
                                  cross=True)
            assert isinstance(t, cmr.DirectTransfer)  # codec off
            out = t(jax.device_put(jnp.asarray(x), src))
            np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


# ---------------------------------------------------------------------
# end-to-end: forced strategies on the unified graph executor
# ---------------------------------------------------------------------

def _run_mlp(mode, strategy="auto", quantize="off", n_steps=2):
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.pipeline_parallel.layer_construction import (
        AutoLayerOption)
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                  get_mlp_train_step)
    global_config.pipeline_dispatch_mode = mode
    global_config.reshard_strategy = strategy
    global_config.reshard_quantize = quantize
    if quantize != "off":
        global_config.reshard_quantize_min_bytes = 1
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=4),
        stage_option=UniformStageOption(num_stages=4))
    step = get_mlp_train_step(method, use_value_and_grad=False)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)
    val = None
    for _ in range(n_steps):
        state, val = step(state, batch)
    return state, val, step.get_last_executable()


@pytest.mark.parametrize("strategy",
                         ["slice_all_gather", "reduce_scatter_gather",
                          "all_to_all"])
def test_forced_strategy_bitwise_on_graph_executor(strategy):
    """The 4-stage donated MLP train step (grouped direct baseline)
    must be bit-identical when every eligible cross-mesh edge is forced
    onto a collective strategy, in both lowered modes."""
    alpa_tpu.init("local")
    state_d, val_d, _ = _run_mlp("registers", "direct_p2p")
    state_c, val_c, ex = _run_mlp("registers", strategy)
    text = ex._register_programs["registers"].text
    if strategy != "all_to_all":
        # these strategies are eligible on this model's replicated
        # destination edges — the program must actually use them
        assert f"strategy={strategy}" in text
    for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                    jax.tree_util.tree_leaves(state_c.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(val_d), np.asarray(val_c))
    state_o, val_o, _ = _run_mlp("overlap", strategy)
    for a, b in zip(jax.tree_util.tree_leaves(state_d.params),
                    jax.tree_util.tree_leaves(state_o.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.asarray(val_d), np.asarray(val_o))


def test_quantized_end_to_end_close_and_weights_lossless():
    """Opt-in int8 codec on the same train step: activation edges are
    quantized (counters move), weight edges (microbatch-invariant,
    ``var_key[1] < 0``) never are, and the loss stays within a few
    percent of the lossless run."""
    from alpa_tpu.telemetry import metrics as _tmetrics
    alpa_tpu.init("local")
    _, val_d, _ = _run_mlp("registers", "direct_p2p")
    reg = _tmetrics.get_registry()
    fam = reg.get("alpa_reshard_quantized_edges_total")
    before = fam.labels("int8").value if fam else 0.0
    _, val_q, ex = _run_mlp("registers", quantize="int8")
    text = ex._register_programs["registers"].text
    assert "strategy=quantized" in text
    for line in text.splitlines():
        if ", -1)" in line:     # weight edge
            assert "strategy=quantized" not in line
    fam = reg.get("alpa_reshard_quantized_edges_total")
    assert fam is not None and fam.labels("int8").value > before
    saved = reg.get("alpa_reshard_quantized_bytes_saved_total")
    assert saved is not None and saved.value > 0
    np.testing.assert_allclose(np.asarray(val_q), np.asarray(val_d),
                               rtol=0.1, atol=1e-3)
