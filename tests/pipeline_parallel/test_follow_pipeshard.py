"""FollowParallel after a pipeshard executable + tied embeddings across
meshes (VERDICT r1 next#10; ref alpa/follow_parallel.py:25 and the
ReplicatedDistributedArray role, alpa/device_mesh.py:1697).

The tied embedding table is consumed by BOTH the first stage (token
embedding) and the last stage (lm head): one logical tensor resident on
two meshes, with gradient contributions from both summed by the runtime.
"""
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from flax.training import train_state

import alpa_tpu
from alpa_tpu import PipeshardParallel
from alpa_tpu.follow_parallel import FollowParallel
from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
from alpa_tpu.model.model_util import cross_entropy_loss
from alpa_tpu.pipeline_parallel.layer_construction import ManualLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import assert_allclose


def _tied_gpt_setup():
    alpa_tpu.init(cluster="local")
    config = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                       seq_len=16, vocab_size=64, tie_embeddings=True,
                       pipeline_boundary_every=1)
    model = GPTModel(config)
    rng = jax.random.PRNGKey(0)
    batch = {
        "input_ids": jax.random.randint(rng, (8, 16), 0, 64),
        "labels": jax.random.randint(jax.random.PRNGKey(1), (8, 16), 0,
                                     64),
    }
    params = model.init(rng, batch["input_ids"])
    tx = optax.sgd(0.01)
    state = train_state.TrainState.create(apply_fn=model.apply,
                                          params=params, tx=tx)
    return model, config, state, batch


def _loss_fn(apply_fn, params, batch):
    logits = apply_fn(params, batch["input_ids"])
    return cross_entropy_loss(logits.astype(jnp.float32), batch["labels"])


class TestFollowPipeshard:

    def test_tied_embeddings_train_then_follow_eval(self):
        model, _config, state, batch = _tied_gpt_setup()
        method = PipeshardParallel(
            num_micro_batches=2, layer_option=ManualLayerOption(),
            stage_option=UniformStageOption(num_stages=2))

        @alpa_tpu.parallelize(method=method, batch_argnums=(1,),
                              donate_argnums=())
        def train_step(state, batch):
            def loss_fn(p):
                return _loss_fn(state.apply_fn, p, batch)
            loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), loss

        # serial oracle: tied-embedding grads must sum the embed + lm-head
        # contributions (one logical tensor on two meshes)
        def serial_step(state, batch):
            def loss_fn(p):
                return _loss_fn(state.apply_fn, p, batch)
            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), loss

        state_p, loss_p = train_step(state, batch)
        # ReplicatedDistributedArray role (ref device_mesh.py:1697): the
        # tied table is one logical tensor placed on BOTH the embedding
        # mesh and the lm-head mesh.
        t_ex = train_step.get_last_executable()
        multi_mesh = [v for v, places in t_ex.input_place.items()
                      if len(places) >= 2]
        assert multi_mesh, "no input replicated across meshes"
        emb_shape = np.asarray(
            state.params["params"]["wte"]["embedding"]).shape
        assert any(tuple(v.aval.shape) == emb_shape for v in multi_mesh), (
            f"tied embedding table not multi-mesh resident: "
            f"{[tuple(v.aval.shape) for v in multi_mesh]}")
        state_s, loss_s = serial_step(state, batch)
        assert_allclose(float(loss_s), float(loss_p), 2e-3, 2e-3)
        assert_allclose(jax.device_get(state_s.params),
                        jax.device_get(state_p.params), 2e-3, 2e-3)

        # eval step follows the train step's placement
        def eval_step(state, batch):
            return _loss_fn(state.apply_fn, state.params, batch)

        follow = FollowParallel(train_step, (state, batch))
        efn = alpa_tpu.parallelize(eval_step, method=follow,
                                   batch_argnums=(1,))
        loss_e = efn(state_p, batch)
        ref = eval_step(jax.device_get(state_p), batch)
        assert_allclose(float(ref), float(loss_e), 2e-3, 2e-3)

        ex = efn.get_last_executable()
        report = getattr(ex, "follow_report", None)
        assert report is not None
        assert report["followed"] > 0
        assert report["mismatched"] == 0, report


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
