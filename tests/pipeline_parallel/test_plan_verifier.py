"""Static plan verifier (ISSUE 8 tentpole).

Oracle 1: a clean real 2-mesh pipeline passes all four analyses at
lowering time (verify_plans defaults to "warn", so the verifier runs on
every compile).  Oracle 2: each mutation class is caught with its named
error — swapped RECV order (deadlock.recv-before-send), dtype-corrupted
RUN (typing.run-input-mismatch), dropped FREE (liveness.leak), a
quantized codec on a weight edge (typing.quantized-weight-edge).
Oracle 3: verdicts are cached in the compile cache and replayed
identically on a warm restart, readable without recompiling
(verify_tool's path).  Oracle 4: verify_plans="error" blocks the launch
of a corrupted program with PlanVerificationError.
"""
import dataclasses
import os

import pytest

import alpa_tpu
from alpa_tpu import PipeshardParallel
from alpa_tpu.analysis import plan_verifier as pv
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)


@pytest.fixture(autouse=True)
def _restore_globals():
    prev_mode = global_config.pipeline_dispatch_mode
    prev_verify = global_config.verify_plans
    prev_dir = global_config.compile_cache_dir
    yield
    global_config.pipeline_dispatch_mode = prev_mode
    global_config.verify_plans = prev_verify
    global_config.compile_cache_dir = prev_dir
    from alpa_tpu.compile_cache import reset_compile_cache
    reset_compile_cache()


def _compile_pipeline(num_stages=2, mode="registers"):
    alpa_tpu.init("local")
    global_config.pipeline_dispatch_mode = mode
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=4),
        stage_option=UniformStageOption(num_stages=num_stages))
    step = get_mlp_train_step(method, use_value_and_grad=False)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)
    state, _ = step(state, batch)
    return step.get_last_executable(), state, batch, step


# ---------------------------------------------------------------------
# oracle 1: clean real program passes every analysis
# ---------------------------------------------------------------------

def test_clean_two_mesh_program_passes_all_analyses():
    ex, *_ = _compile_pipeline(num_stages=2)
    prog = ex._register_programs["registers"]
    verdict = prog.verdict
    assert verdict is not None, \
        "verify_plans defaults to 'warn': every lowering must verify"
    assert verdict.ok, verdict.format_table()
    assert verdict.errors == []
    st = verdict.stats
    # all four analyses ran over a real program with real structure
    assert st["n_ops"] > 0 and st["n_slots"] > 0
    assert st["n_cross_mesh"] > 0, "2-mesh pipeline must cross meshes"
    assert st["n_channels"] >= 1
    assert st["num_meshes"] == 2
    # liveness computed a nonzero static peak for every mesh
    peaks = st["peak_bytes"]
    assert set(peaks) == {"0", "1"}
    assert all(b > 0 for b in peaks.values()), peaks
    # a clean program leaks nothing (FREE emission is complete)
    assert st["leaked_slots"] == 0, st["leaked_vars"]
    assert "PASS" in verdict.format_table()


def test_verify_off_skips_and_attaches_no_verdict():
    global_config.verify_plans = "off"
    ex, *_ = _compile_pipeline(num_stages=2)
    assert ex._register_programs["registers"].verdict is None


def test_verdict_surfaces_in_debug_dump(tmp_path):
    from alpa_tpu.monitoring import dump_debug_info
    ex, *_ = _compile_pipeline(num_stages=2)
    dump_debug_info(ex, str(tmp_path))
    path = tmp_path / "plan_verdict.txt"
    assert path.exists()
    assert "plan verdict: PASS" in path.read_text()


# ---------------------------------------------------------------------
# oracle 2: mutation classes, hand-built 2-mesh models
# ---------------------------------------------------------------------

_F32 = "float32"
_AVAL = ((4, 4), _F32)


def _slots():
    return {
        0: pv.SlotModel(0, "x@m0", 0, 0, (4, 4), _F32, 64,
                        preplaced=True),
        1: pv.SlotModel(1, "h0@m0", 0, 0, (4, 4), _F32, 64),
        2: pv.SlotModel(2, "h0@m1", 0, 1, (4, 4), _F32, 64),
        3: pv.SlotModel(3, "out@m1", 0, 1, (4, 4), _F32, 64,
                        protected=True),
    }


def _ops():
    return [
        pv.OpModel(0, "RUN", 0, reads=(0,), writes=(1,),
                   in_avals=(_AVAL,), out_avals=(_AVAL,),
                   label="RUN stage0"),
        pv.OpModel(1, "RESHARD", 0, reads=(1,), writes=(2,),
                   edge=(0, 1), cross=True, nbytes=64,
                   label="RESHARD h0 0->1"),
        pv.OpModel(2, "RUN", 1, reads=(2,), writes=(3,),
                   in_avals=(_AVAL,), out_avals=(_AVAL,),
                   label="RUN stage1"),
        pv.OpModel(3, "FREE", 0, kills=(1,), label="FREE h0@m0"),
        pv.OpModel(4, "FREE", 1, kills=(2,), label="FREE h0@m1"),
    ]


def _model(ops, slots=None, streams=None, deps=None):
    return pv.PlanModel(
        ops=ops, slots=slots or _slots(), num_meshes=2,
        streams=streams or [[0, 1, 3], [2, 4]],
        deps=deps if deps is not None else {2: {1}})


def _codes(verdict):
    return {f.code for f in verdict.findings()}


def test_hand_built_clean_model_passes():
    verdict = pv.verify_model(_model(_ops()))
    assert verdict.ok and not verdict.warnings, verdict.format_table()


def test_mutation_swapped_recv_order_is_deadlock():
    """The cross-mesh transfer ordered before its producer: the RECV
    side would wait forever on a SEND that was never issued."""
    ops = _ops()
    # swap the RESHARD in front of the stage that produces its payload
    ops[0], ops[1] = ops[1], ops[0]
    ops[0] = dataclasses.replace(ops[0], idx=0)
    ops[1] = dataclasses.replace(ops[1], idx=1)
    verdict = pv.verify_model(_model(ops))
    assert not verdict.ok
    assert "deadlock.recv-before-send" in _codes(verdict), \
        verdict.format_table()


def test_mutation_dependency_cycle_is_deadlock():
    """Two streams waiting on each other: Kahn's pass reports the cycle
    with the stuck ops named."""
    verdict = pv.verify_model(_model(_ops(), deps={2: {1}, 1: {2}}))
    assert not verdict.ok
    assert "deadlock.cycle" in _codes(verdict), verdict.format_table()


def test_mutation_dtype_corrupted_run_is_typing_error():
    ops = _ops()
    ops[2] = dataclasses.replace(ops[2],
                                 in_avals=(((4, 4), "bfloat16"),))
    verdict = pv.verify_model(_model(ops))
    assert not verdict.ok
    assert "typing.run-input-mismatch" in _codes(verdict), \
        verdict.format_table()
    [finding] = [f for f in verdict.errors
                 if f.code == "typing.run-input-mismatch"]
    assert "h0@m1" in finding.message      # names the corrupted value
    assert finding.op == 2


def test_mutation_dropped_free_is_leak_with_var_names():
    ops = _ops()[:-1]                      # drop FREE h0@m1
    verdict = pv.verify_model(_model(ops, streams=[[0, 1, 3], [2]]))
    assert verdict.ok                      # leak is a warning, not error
    assert "liveness.leak" in _codes(verdict), verdict.format_table()
    [finding] = [f for f in verdict.warnings
                 if f.code == "liveness.leak"]
    assert "h0@m1" in finding.message
    assert verdict.stats["leaked_slots"] == 1
    assert verdict.stats["leaked_vars"] == ["h0@m1"]


def test_mutation_quantized_weight_edge_is_rejected():
    ops = _ops()
    ops[1] = dataclasses.replace(ops[1], strategy="quantized",
                                 weight=True, groupable=False)
    verdict = pv.verify_model(_model(ops))
    assert not verdict.ok
    assert "typing.quantized-weight-edge" in _codes(verdict), \
        verdict.format_table()
    [finding] = [f for f in verdict.errors
                 if f.code == "typing.quantized-weight-edge"]
    assert "losslessly" in finding.message


def test_byte_mismatched_endpoints_is_deadlock():
    slots = _slots()
    slots[2] = dataclasses.replace(slots[2], nbytes=128)
    verdict = pv.verify_model(_model(_ops(), slots=slots))
    assert "deadlock.byte-mismatch" in _codes(verdict), \
        verdict.format_table()


def test_double_free_and_use_after_free_are_errors():
    ops = _ops() + [pv.OpModel(5, "FREE", 1, kills=(2,),
                               label="FREE h0@m1 again")]
    verdict = pv.verify_model(_model(
        ops, streams=[[0, 1, 3], [2, 4, 5]]))
    assert "liveness.double-free" in _codes(verdict)

    ops = _ops() + [pv.OpModel(5, "RUN", 1, reads=(2,), writes=(3,),
                               label="RUN after free")]
    verdict = pv.verify_model(_model(
        ops, streams=[[0, 1, 3], [2, 4, 5]]))
    assert "liveness.use-after-free" in _codes(verdict)


# ---------------------------------------------------------------------
# structure analysis: hooks, groups (grouped/coalesced RESHARDs)
# ---------------------------------------------------------------------

def _hook(name, node, members, reads=(), writes=(), kills=()):
    from alpa_tpu.pipeline_parallel.runtime_emitter import OpHook
    return OpHook(kind="exec", name=name, node=node, mesh=0,
                  reads=tuple(reads), writes=tuple(writes),
                  kills=tuple(kills),
                  slots=tuple(reads) + tuple(writes) + tuple(kills),
                  members=tuple(members))


def test_hook_footprint_must_match_member_union():
    ops = _ops()
    good = _hook("RESHARD h0", 1, (1,), reads=(1,), writes=(2,))
    verdict = pv.verify_model(_model(ops), hooks=[good])
    assert "structure.hook-footprint" not in _codes(verdict)

    bad = _hook("RESHARD h0", 1, (1,), reads=(1,), writes=())  # lost dst
    verdict = pv.verify_model(_model(ops), hooks=[bad])
    assert "structure.hook-footprint" in _codes(verdict), \
        verdict.format_table()


def test_grouped_reshard_hooks_are_member_unions():
    """A coalesced 2-transfer group: the group hook's footprint is the
    union of both members; collective-strategy members may not join."""
    slots = _slots()
    slots[4] = pv.SlotModel(4, "h1@m0", 0, 0, (4, 4), _F32, 64)
    slots[5] = pv.SlotModel(5, "h1@m1", 0, 1, (4, 4), _F32, 64)
    ops = [
        pv.OpModel(0, "RUN", 0, reads=(0,), writes=(1, 4),
                   in_avals=(_AVAL,), out_avals=(_AVAL, _AVAL),
                   label="RUN stage0"),
        pv.OpModel(1, "RESHARD", 0, reads=(1,), writes=(2,),
                   edge=(0, 1), cross=True, label="RESHARD h0"),
        pv.OpModel(2, "RESHARD", 0, reads=(4,), writes=(5,),
                   edge=(0, 1), cross=True, label="RESHARD h1"),
        pv.OpModel(3, "RUN", 1, reads=(2, 5), writes=(3,),
                   in_avals=(_AVAL, _AVAL), out_avals=(_AVAL,),
                   label="RUN stage1"),
        pv.OpModel(4, "FREE", 0, kills=(1, 4), label="FREE m0"),
        pv.OpModel(5, "FREE", 1, kills=(2, 5), label="FREE m1"),
    ]
    model = _model(ops, slots=slots, streams=[[0, 1, 2, 4], [3, 5]],
                   deps={3: {1, 2}})
    group = _hook("RESHARDx2", 1, (1, 2), reads=(1, 4), writes=(2, 5))
    verdict = pv.verify_model(model, hooks=[group])
    assert verdict.ok and not verdict.warnings, verdict.format_table()

    # a collective member in a coalesced group must be rejected
    bad_ops = list(ops)
    bad_ops[2] = dataclasses.replace(ops[2], strategy="all_to_all",
                                     groupable=False)
    verdict = pv.verify_model(
        _model(bad_ops, slots=slots, streams=[[0, 1, 2, 4], [3, 5]],
               deps={3: {1, 2}}), hooks=[group])
    assert "structure.group-nongroupable" in _codes(verdict), \
        verdict.format_table()


def test_graph_check_validates_reshard_structure():
    """Regression for the extended InstructionDataflowGraph.check():
    RESHARD nodes must carry a mesh edge, a consistent cross_mesh flag,
    and a single-read/single-write footprint."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        DataflowNode, InstructionDataflowGraph)

    def graph_of(node):
        run = DataflowNode(idx=0, kind="RUN", reads=(), writes=(1,))
        return InstructionDataflowGraph.build(
            [run, dataclasses.replace(node, idx=1)])

    ok = DataflowNode(idx=1, kind="RESHARD", reads=(1,), writes=(2,),
                      edge=(0, 1), cross_mesh=True)
    graph_of(ok).check()

    with pytest.raises(RuntimeError, match="no mesh edge"):
        graph_of(dataclasses.replace(ok, edge=None)).check()
    with pytest.raises(RuntimeError, match="disagrees with edge"):
        graph_of(dataclasses.replace(ok, cross_mesh=False)).check()
    with pytest.raises(RuntimeError, match="exactly one"):
        graph_of(dataclasses.replace(ok, writes=(2, 3))).check()


# ---------------------------------------------------------------------
# oracle 3: verdict caching — identical replay on warm restart
# ---------------------------------------------------------------------

def test_verdict_cache_replay_identical_on_warm_restart(tmp_path):
    from alpa_tpu.compile_cache import (get_compile_cache,
                                        reset_compile_cache)
    global_config.compile_cache_dir = str(tmp_path)
    reset_compile_cache()
    ex, *_ = _compile_pipeline(num_stages=2)
    cold = ex._register_programs["registers"].verdict
    assert cold is not None and cold.ok

    # warm restart: wipe the lowering (but not the disk cache) and the
    # in-memory cache tier, then lower again
    reset_compile_cache()
    ex._register_programs = {}
    ex._register_program = None
    ex._ensure_lowered("registers")
    warm = ex._register_programs["registers"].verdict
    assert warm.to_dict() == cold.to_dict()
    stats = get_compile_cache().stats()["namespaces"]["plan_verdict"]
    assert stats["hits"] >= 1, stats

    # verify_tool's no-recompile path reads the same verdict back
    cached = pv.load_cached_verdicts()
    assert cached, "no plan_verdict entries on disk"
    assert cached[0]["verdict"].to_dict() == cold.to_dict()


# ---------------------------------------------------------------------
# oracle 4: verify_plans="error" blocks the launch of a broken program
# ---------------------------------------------------------------------

def test_verify_error_policy_blocks_launch():
    """Appending a second FREE of the same keys makes the program
    double-free; under verify_plans='error' the lowering (and therefore
    the launch) must be refused with the named finding."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        PipelineInstType)
    ex, state, batch, step = _compile_pipeline(num_stages=2)
    free = next(i for i in ex.instructions
                if i.opcode == PipelineInstType.FREE)
    ex.instructions.append(free)
    ex._register_programs = {}
    ex._register_program = None
    global_config.verify_plans = "error"
    try:
        with pytest.raises(pv.PlanVerificationError) as exc_info:
            step(state, batch)
        assert "liveness.double-free" in str(exc_info.value)
        assert not exc_info.value.verdict.ok
    finally:
        # leave the executable launchable for other tests' executables
        ex.instructions.pop()
        ex._register_programs = {}
        ex._register_program = None


def test_leak_metrics_and_flight_annotation():
    """A dropped FREE on a real program: the leak is reported on the
    alpa_plan_leaked_slots_total counter and noted in flight dumps."""
    from alpa_tpu.pipeline_parallel.runtime_emitter import (
        PipelineInstType)
    from alpa_tpu.telemetry import flight as tflight
    ex, *_ = _compile_pipeline(num_stages=2)
    idx = next(i for i, inst in enumerate(ex.instructions)
               if inst.opcode == PipelineInstType.FREE)
    dropped = ex.instructions.pop(idx)
    ex._register_programs = {}
    ex._register_program = None
    tflight.clear_annotations()
    before = pv._LEAKED_SLOTS.value
    try:
        prog = ex._ensure_lowered("registers")
        verdict = prog.verdict
        assert verdict.ok                  # warn-level finding
        assert verdict.stats["leaked_slots"] > 0
        assert pv._LEAKED_SLOTS.value > before
        notes = tflight.get_annotations()
        assert notes.get("leaked_slots") == verdict.stats["leaked_vars"]
    finally:
        ex.instructions.insert(idx, dropped)
        ex._register_programs = {}
        ex._register_program = None
        tflight.clear_annotations()
