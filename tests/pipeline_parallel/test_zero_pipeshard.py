"""ZeRO weight-update sharding on pipeshard (ISSUE 10): each stage's
apply_grad runs with optimizer state sharded over that stage's submesh
data-parallel replicas, and the plan verifier proves the per-device
optimizer-state reduction statically (``alpa_opt_state_bytes{mesh}``,
``alpa_plan_peak_bytes{mesh}``) — before anything runs.
"""
import numpy as np

import alpa_tpu
from alpa_tpu import PipeshardParallel
from alpa_tpu.pipeline_parallel.layer_construction import ManualLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
from alpa_tpu.testing import (assert_allclose,
                              create_mlp_train_state_and_batch,
                              get_mlp_train_step)


def _run_pipeshard(zero_stage, n_steps=2):
    alpa_tpu.init(cluster="local")
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=ManualLayerOption(),
        stage_option=UniformStageOption(num_stages=2),
        pipeline_schedule="1f1b",
        default_auto_sharding_option=AutoShardingOption(
            zero_stage=zero_stage))
    state_p, batch = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    state_s, _ = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    pstep = get_mlp_train_step(method, use_value_and_grad=True)
    serial = get_mlp_train_step(None)
    for _ in range(n_steps):
        state_p, loss_p = pstep(state_p, batch)
        state_s, loss_s = serial(state_s, batch)
    assert_allclose(float(loss_s), float(loss_p), 2e-3, 2e-3)
    return float(loss_p), pstep.get_last_executable()


class TestZeroPipeshard:

    def test_zero2_two_stage_matches_serial_and_shrinks_opt_state(self):
        loss0, ex0 = _run_pipeshard("0")
        loss2, ex2 = _run_pipeshard("2")
        # layout change only: both agree with serial (asserted inside)
        # and with each other bitwise
        np.testing.assert_array_equal(np.float32(loss0),
                                      np.float32(loss2))

        v0 = ex0.get_plan_verdict()
        v2 = ex2.get_plan_verdict()
        opt0 = sum(v0.stats["opt_state_bytes"].values())
        opt2 = sum(v2.stats["opt_state_bytes"].values())
        assert opt0 > 0 and opt2 > 0
        # acceptance: per-device opt-state bytes drop >= (dp - eps)x;
        # each 2-stage submesh of the 8-device test mesh has dp = 4
        dp = max(m.num_devices for m in ex2.mesh_group)
        assert opt0 / opt2 >= dp - 0.25, (opt0, opt2, dp)
        # the saving is attributed, and peak memory proves it statically
        assert v2.stats["zero_bytes_saved"] > 0
        assert v0.stats["zero_bytes_saved"] == 0
        peak0 = sum(v0.stats["peak_bytes"].values())
        peak2 = sum(v2.stats["peak_bytes"].values())
        assert peak2 < peak0
        # zero_stage is covered by the plan fingerprint (resume safety)
        assert ex0.get_plan_fingerprint() != ex2.get_plan_fingerprint()

    def test_opt_state_gauges_exported(self):
        from alpa_tpu.telemetry import metrics as tmetrics
        _run_pipeshard("2")
        text = tmetrics.get_registry().to_prometheus_text()
        assert "alpa_opt_state_bytes" in text
        assert "alpa_zero_bytes_saved_total" in text
