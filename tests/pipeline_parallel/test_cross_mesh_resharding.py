"""Cross-mesh resharding planner tests
(ref tests/pipeline_parallel/test_cross_mesh_resharding.py:30-120)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
    ReshardingTask, Tile, VirtualDistributedArray, plan_resharding)


def _mesh(n, names=("x",), shape=None):
    devs = np.array(jax.devices()[:n])
    if shape:
        devs = devs.reshape(shape)
    return Mesh(devs, names)


class TestTileMath:

    def test_intersect(self):
        a = Tile(((0, 4), (0, 8)))
        b = Tile(((2, 6), (4, 12)))
        c = a.intersect(b)
        assert c.slices == ((2, 4), (4, 8))
        assert c.size == 8
        assert a.intersect(Tile(((4, 8), (0, 8)))) is None

    def test_vda_from_sharding(self):
        mesh = _mesh(4)
        s = NamedSharding(mesh, P("x"))
        vda = VirtualDistributedArray.from_sharding((8, 4), s)
        assert len(vda.device_tiles) == 4
        # tiles partition the rows
        rows = sorted(t.slices[0] for t in vda.device_tiles)
        assert rows == [(0, 2), (2, 4), (4, 6), (6, 8)]

    def test_vda_replicated(self):
        mesh = _mesh(4)
        s = NamedSharding(mesh, P())
        vda = VirtualDistributedArray.from_sharding((8,), s)
        uniq = vda.unique_tiles
        assert len(uniq) == 1
        assert len(next(iter(uniq.values()))) == 4


class TestPlanning:

    def test_plan_covers_destination(self):
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]), ("y",))
        src = NamedSharding(src_mesh, P("x"))        # row sharded 4-way
        dst = NamedSharding(dst_mesh, P(None, "y"))  # col sharded 4-way
        spec = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=False)
        # every dst tile fully covered
        for req in spec.requests:
            covered = sum(s.tile.size for s in req.srcs)
            assert covered == req.dst_tile.size
        # row x col intersection: 4 pieces per destination tile
        assert spec.total_tiles() == 16

    def test_load_balanced_sources(self):
        """Replicated source: transfers spread across source shards."""
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]), ("y",))
        src = NamedSharding(src_mesh, P())       # replicated on 4
        dst = NamedSharding(dst_mesh, P("y"))
        spec = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=False)
        used_srcs = {s.src_shard_index for r in spec.requests
                     for s in r.srcs}
        assert len(used_srcs) >= 2, "all transfers pinned to one source"

    def test_allgather_rewrite_reduces_bytes(self):
        """dst replicated -> rewrite sends 1/k slices + intra-mesh gather
        (MLSys'23 local-allgather optimization)."""
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]), ("y",))
        src = NamedSharding(src_mesh, P("x"))
        dst = NamedSharding(dst_mesh, P())       # fully replicated dst
        naive = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=False)
        smart = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=True)
        assert smart.allgather_rewrite
        assert smart.transfer_bytes < naive.transfer_bytes
        # ideal: k-fold reduction (k = 4 replicas)
        assert smart.transfer_bytes * 4 <= naive.transfer_bytes + 1e-6

    def test_execution_matches_device_put(self):
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]).reshape(2, 2),
                        ("a", "b"))
        src = NamedSharding(src_mesh, P("x"))
        dst = NamedSharding(dst_mesh, P("b", "a"))
        x = jax.device_put(jnp.arange(64.0).reshape(8, 8), src)
        spec = plan_resharding((8, 8), 4, src, dst)
        task = ReshardingTask(spec, dst)
        y = task.run(x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        assert y.sharding.is_equivalent_to(dst, 2)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
