"""Cross-mesh resharding planner tests
(ref tests/pipeline_parallel/test_cross_mesh_resharding.py:30-120)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
    ReshardingTask, Tile, VirtualDistributedArray, plan_resharding)


def _mesh(n, names=("x",), shape=None):
    devs = np.array(jax.devices()[:n])
    if shape:
        devs = devs.reshape(shape)
    return Mesh(devs, names)


class TestTileMath:

    def test_intersect(self):
        a = Tile(((0, 4), (0, 8)))
        b = Tile(((2, 6), (4, 12)))
        c = a.intersect(b)
        assert c.slices == ((2, 4), (4, 8))
        assert c.size == 8
        assert a.intersect(Tile(((4, 8), (0, 8)))) is None

    def test_vda_from_sharding(self):
        mesh = _mesh(4)
        s = NamedSharding(mesh, P("x"))
        vda = VirtualDistributedArray.from_sharding((8, 4), s)
        assert len(vda.device_tiles) == 4
        # tiles partition the rows
        rows = sorted(t.slices[0] for t in vda.device_tiles)
        assert rows == [(0, 2), (2, 4), (4, 6), (6, 8)]

    def test_vda_replicated(self):
        mesh = _mesh(4)
        s = NamedSharding(mesh, P())
        vda = VirtualDistributedArray.from_sharding((8,), s)
        uniq = vda.unique_tiles
        assert len(uniq) == 1
        assert len(next(iter(uniq.values()))) == 4


class TestPlanning:

    def test_plan_covers_destination(self):
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]), ("y",))
        src = NamedSharding(src_mesh, P("x"))        # row sharded 4-way
        dst = NamedSharding(dst_mesh, P(None, "y"))  # col sharded 4-way
        spec = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=False)
        # every dst tile fully covered
        for req in spec.requests:
            covered = sum(s.tile.size for s in req.srcs)
            assert covered == req.dst_tile.size
        # row x col intersection: 4 pieces per destination tile
        assert spec.total_tiles() == 16

    def test_load_balanced_sources(self):
        """Replicated source: transfers spread across source shards."""
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]), ("y",))
        src = NamedSharding(src_mesh, P())       # replicated on 4
        dst = NamedSharding(dst_mesh, P("y"))
        spec = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=False)
        used_srcs = {s.src_shard_index for r in spec.requests
                     for s in r.srcs}
        assert len(used_srcs) >= 2, "all transfers pinned to one source"

    def test_allgather_rewrite_reduces_bytes(self):
        """dst replicated -> rewrite sends 1/k slices + intra-mesh gather
        (MLSys'23 local-allgather optimization)."""
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]), ("y",))
        src = NamedSharding(src_mesh, P("x"))
        dst = NamedSharding(dst_mesh, P())       # fully replicated dst
        naive = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=False)
        smart = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=True)
        assert smart.allgather_rewrite
        assert smart.transfer_bytes < naive.transfer_bytes
        # ideal: k-fold reduction (k = 4 replicas)
        assert smart.transfer_bytes * 4 <= naive.transfer_bytes + 1e-6

    def test_execution_matches_device_put(self):
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]).reshape(2, 2),
                        ("a", "b"))
        src = NamedSharding(src_mesh, P("x"))
        dst = NamedSharding(dst_mesh, P("b", "a"))
        x = jax.device_put(jnp.arange(64.0).reshape(8, 8), src)
        spec = plan_resharding((8, 8), 4, src, dst)
        task = ReshardingTask(spec, dst)
        y = task.run(x)
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        assert y.sharding.is_equivalent_to(dst, 2)


class TestPlannedExecution:
    """The executor drives the plan literally: executed cross-mesh bytes
    must equal the spec's accounting (VERDICT r1 next#5; ref
    SymbolicReshardingTask :418 send/recv + :935 broadcast)."""

    def _src_dst(self):
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]), ("y",))
        return src_mesh, dst_mesh

    def test_tiled_bytes_match_plan(self):
        src_mesh, dst_mesh = self._src_dst()
        src = NamedSharding(src_mesh, P("x"))        # rows 4-way
        dst = NamedSharding(dst_mesh, P(None, "y"))  # cols 4-way
        x = jax.device_put(jnp.arange(64.0, dtype=jnp.float32)
                           .reshape(8, 8), src)
        spec = plan_resharding((8, 8), x.dtype.itemsize, src, dst,
                               allow_allgather_rewrite=False)
        task = ReshardingTask(spec, dst)
        y = task.run(x, mode="tiled")
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        assert y.sharding.is_equivalent_to(dst, 2)
        assert task.last_report.cross_mesh_bytes == spec.transfer_bytes
        assert task.last_report.intra_mesh_bytes == 0

    def test_multiprocess_wire_bytes_accounting(self):
        """run_multiprocess packs tiles in a widened psum work dtype;
        wire_bytes must reflect that (2x planned for bf16), while
        cross_mesh_bytes stays planned-payload bytes (ADVICE r3)."""
        src_mesh, dst_mesh = self._src_dst()
        src = NamedSharding(src_mesh, P("x"))
        dst = NamedSharding(dst_mesh, P(None, "y"))
        x = jax.device_put(jnp.arange(64.0, dtype=jnp.bfloat16)
                           .reshape(8, 8), src)
        spec = plan_resharding((8, 8), x.dtype.itemsize, src, dst,
                               allow_allgather_rewrite=False)
        task = ReshardingTask(spec, dst)
        y = task.run_multiprocess(x)
        np.testing.assert_array_equal(np.asarray(y, np.float32),
                                      np.arange(64.0).reshape(8, 8))
        rep = task.last_report
        assert rep.cross_mesh_bytes == spec.transfer_bytes
        assert rep.wire_bytes == 2 * rep.cross_mesh_bytes

    def test_allgather_rewrite_executes_fewer_cross_bytes(self):
        src_mesh, dst_mesh = self._src_dst()
        src = NamedSharding(src_mesh, P("x"))
        dst = NamedSharding(dst_mesh, P())   # fully replicated dst
        x = jax.device_put(jnp.arange(64.0, dtype=jnp.float32)
                           .reshape(8, 8), src)
        naive = plan_resharding((8, 8), 4, src, dst,
                                allow_allgather_rewrite=False)
        smart = plan_resharding((8, 8), 4, src, dst,
                                allow_allgather_rewrite=True)
        t_naive = ReshardingTask(naive, dst)
        y1 = t_naive.run(x, mode="tiled")
        t_smart = ReshardingTask(smart, dst)
        y2 = t_smart.run(x, mode="tiled")
        np.testing.assert_array_equal(np.asarray(y1), np.asarray(x))
        np.testing.assert_array_equal(np.asarray(y2), np.asarray(x))
        # executed bytes == planned bytes in both modes; the rewrite's
        # cross-mesh leg is k=4x smaller, paid for by intra-mesh gather
        assert t_naive.last_report.cross_mesh_bytes == naive.transfer_bytes
        assert t_smart.last_report.cross_mesh_bytes == smart.transfer_bytes
        assert (t_smart.last_report.cross_mesh_bytes * 4
                <= t_naive.last_report.cross_mesh_bytes + 1e-6)
        assert t_smart.last_report.intra_mesh_bytes > 0

    def test_broadcast_mode_unique_tiles_cross_once(self):
        src_mesh, dst_mesh = self._src_dst()
        src = NamedSharding(src_mesh, P("x"))
        dst = NamedSharding(dst_mesh, P())   # every dst device = full array
        x = jax.device_put(jnp.arange(64.0, dtype=jnp.float32)
                           .reshape(8, 8), src)
        spec = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=False)
        task = ReshardingTask(spec, dst)
        y = task.run(x, mode="broadcast")
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        # the full array crosses exactly once (256 B), not once per replica
        assert task.last_report.cross_mesh_bytes == 8 * 8 * 4
        assert task.last_report.intra_mesh_bytes > 0

    def test_scalar_transfer_accounted(self):
        """0-d arrays (e.g. the loss) go through the planned path too —
        executed bytes must match the plan, not silently report zero."""
        src_mesh, dst_mesh = self._src_dst()
        src = NamedSharding(src_mesh, P())
        dst = NamedSharding(dst_mesh, P())
        x = jax.device_put(jnp.float32(3.25), src)
        spec = plan_resharding((), 4, src, dst)
        task = ReshardingTask(spec, dst)
        y = task.run(x, mode="tiled")
        assert float(y) == 3.25
        assert task.last_report.cross_mesh_bytes == spec.transfer_bytes
        assert task.last_report.mode == "tiled"

    def test_permuted_2d_dst_tiled(self):
        src_mesh = _mesh(4, shape=(2, 2), names=("a", "b"))
        dst_mesh = Mesh(np.array(jax.devices()[4:8]).reshape(2, 2),
                        ("c", "d"))
        src = NamedSharding(src_mesh, P("a", "b"))
        dst = NamedSharding(dst_mesh, P("d", None))
        x = jax.device_put(jnp.arange(96.0, dtype=jnp.float32)
                           .reshape(8, 12), src)
        spec = plan_resharding((8, 12), 4, src, dst)
        task = ReshardingTask(spec, dst)
        y = task.run(x, mode="tiled")
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        assert task.last_report.cross_mesh_bytes == spec.transfer_bytes


class TestPipeshardPlannedExecution:
    """End-to-end: a pipelined step under resharding_execution='planned'
    matches the device_put fast path bit-for-bit and reports executed
    bytes (SURVEY §4 strategy 5)."""

    @pytest.mark.parametrize("mode", ["send_recv", "broadcast"])
    def test_pipeshard_numerics_and_accounting(self, mode):
        import alpa_tpu
        from alpa_tpu import PipeshardParallel
        from alpa_tpu.global_env import global_config
        from alpa_tpu.pipeline_parallel.layer_construction import (
            ManualLayerOption)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            UniformStageOption)
        from alpa_tpu.testing import (assert_allclose,
                                      create_mlp_train_state_and_batch,
                                      get_mlp_train_step)

        alpa_tpu.init(cluster="local")
        method = PipeshardParallel(
            num_micro_batches=2, layer_option=ManualLayerOption(),
            stage_option=UniformStageOption(num_stages=2))
        state_p, batch = create_mlp_train_state_and_batch(
            batch_size=64, num_layers=4, manual_pipeline_layer=True)
        state_s, _ = create_mlp_train_state_and_batch(
            batch_size=64, num_layers=4, manual_pipeline_layer=True)

        old_exec = global_config.resharding_execution
        old_mode = global_config.resharding_mode
        global_config.resharding_execution = "planned"
        global_config.resharding_mode = mode
        try:
            pstep = get_mlp_train_step(method, use_value_and_grad=True)
            serial = get_mlp_train_step(None)
            state_p, loss_p = pstep(state_p, batch)
            state_s, loss_s = serial(state_s, batch)
            ex = pstep.get_last_executable()
            report = ex.get_resharding_report()
        finally:
            global_config.resharding_execution = old_exec
            global_config.resharding_mode = old_mode
        assert_allclose(float(loss_s), float(loss_p), 2e-3, 2e-3)
        assert_allclose(jax.device_get(state_s.params),
                        jax.device_get(state_p.params), 2e-3, 2e-3)
        if ex._resharding_bytes:
            assert ex._executed_resharding_bytes > 0
            assert "executed" in report


class TestLinkAccounting:
    """Byte-accounting audit + broadcast load balancing (ISSUE 4).

    One fully pinned scenario — rows sharded 4-way (devices 0-3) to
    fully replicated on a second 4-device mesh (devices 4-7), shape
    (8, 8) f32, allgather rewrite off so S = 8*8*4 = 256 B:

    * send_recv accounting counts once PER REPLICA: 4S = 1024 B;
    * broadcast accounting counts each unique tile ONCE: S = 256 B
      (the pre-audit report multiplied broadcast bytes by the
      replication factor);
    * naive broadcast routing lands all 4 unique 64 B tiles on the
      replica group's first holder (ingress 256 B); balanced routing
      spreads them, 64 B per member — a 4x max-link reduction.
    """

    S = 8 * 8 * 4          # full-array payload bytes

    def _spec(self):
        src_mesh = _mesh(4)
        dst_mesh = Mesh(np.array(jax.devices()[4:8]), ("y",))
        src = NamedSharding(src_mesh, P("x"))   # rows 4-way
        dst = NamedSharding(dst_mesh, P())      # replicated x4
        spec = plan_resharding((8, 8), 4, src, dst,
                               allow_allgather_rewrite=False)
        return spec, src, dst

    def test_pinned_send_recv_vs_broadcast_totals(self):
        from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
            naive_transfer_bytes)
        spec, _, dst = self._spec()
        # send_recv: every replica fetches the full array
        assert spec.transfer_bytes == 4 * self.S == 1024
        assert naive_transfer_bytes((8, 8), 4, dst,
                                    mode="send_recv") == 4 * self.S
        # broadcast: the unique destination tile crosses exactly once
        assert spec.broadcast_bytes == self.S == 256
        assert naive_transfer_bytes((8, 8), 4, dst,
                                    mode="broadcast") == self.S

    def test_pinned_broadcast_max_link_balanced_vs_naive(self):
        from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
            compute_link_loads)
        spec, _, _ = self._spec()
        # naive: all 4 unique 64 B tiles converge on the first holder
        assert spec.max_link_bytes_broadcast_naive == self.S == 256
        # balanced: one tile per member; every link carries 64 B
        assert spec.max_link_bytes_broadcast == self.S / 4 == 64
        loads = compute_link_loads(spec, broadcast=True, loadbalance=True)
        assert set(loads["ingress"].values()) == {64.0}
        assert set(loads["egress"].values()) == {64.0}
        nloads = compute_link_loads(spec, broadcast=True,
                                    loadbalance=False)
        assert max(nloads["ingress"].values()) == 256.0

    def test_pinned_send_recv_max_link(self):
        spec, _, _ = self._spec()
        # each src row shard feeds all 4 replicas (4 * 64 B egress);
        # each dst replica ingests the full array (256 B) — balancing
        # cannot help: every piece has exactly one holder and one taker
        assert spec.max_link_bytes == self.S == 256
        assert spec.max_link_bytes_naive == self.S

    def test_send_order_interleaves_sources(self):
        spec, _, _ = self._spec()
        order = spec.send_order
        all_moves = {(ri, si) for ri, req in enumerate(spec.requests)
                     for si in range(len(req.srcs))}
        assert set(order) == all_moves and len(order) == len(all_moves)
        # greedy least-issued-egress: the first 4 moves come from 4
        # DISTINCT source devices (plan order would drain one request —
        # all 4 of its pieces — before touching the next)
        first_devs = [
            spec.src_device_ids[
                spec.requests[ri].srcs[si].src_shard_index]
            for ri, si in order[:4]]
        assert len(set(first_devs)) == 4

    def test_executed_report_matches_planned_max_link(self):
        from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
            compute_link_loads)
        spec, src, dst = self._spec()
        x = jax.device_put(jnp.arange(64.0, dtype=jnp.float32)
                           .reshape(8, 8), src)
        task = ReshardingTask(spec, dst)
        y = task.run(x, mode="tiled")
        np.testing.assert_array_equal(np.asarray(y), np.asarray(x))
        loads = compute_link_loads(spec, broadcast=False)
        assert task.last_report.max_link_bytes == loads["max_link_bytes"]

    def test_pinned_slice_all_gather_strategy_stats(self):
        """Collective lowering (ISSUE 7): for the same pinned 4+4 plan
        the ``slice_all_gather`` wire leg must move the whole array
        exactly once — at most the pinned 256 B broadcast figure — with
        one 64 B message per link; ``direct_p2p`` pays 4 messages and
        256 B on the busiest link.  A selection or link-stats regression
        fails here."""
        spec, _, _ = self._spec()
        stats = spec.strategy_stats
        assert {"direct_p2p", "slice_all_gather"} <= set(stats)
        sag = stats["slice_all_gather"]
        assert sag["total_bytes"] == self.S == 256     # ≤ broadcast 256
        assert sag["max_link_bytes"] == self.S / 4 == 64
        assert sag["max_link_messages"] == 1
        direct = stats["direct_p2p"]
        assert direct["total_bytes"] == 4 * self.S == 1024
        assert direct["max_link_bytes"] == self.S == 256
        assert direct["max_link_messages"] == 4
        # default knobs (no emulated latency): ties resolve to direct,
        # keeping the default path byte-identical to pre-ISSUE-7
        assert spec.strategy == "direct_p2p"
        assert set(spec.strategy_costs) == set(stats)

    def test_planner_counters_accumulate(self):
        from alpa_tpu.pipeline_parallel.cross_mesh_resharding import (
            get_planner_stats, reset_planner_stats)
        reset_planner_stats()
        try:
            self._spec()
            st = get_planner_stats()
            assert st["plans"] == 1
            assert st["total_bytes"] == 4 * self.S
            assert st["broadcast_bytes"] == self.S
            assert st["max_link_bytes"] == self.S        # send_recv link
            assert st["max_link_bytes_naive"] == self.S
        finally:
            reset_planner_stats()


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
