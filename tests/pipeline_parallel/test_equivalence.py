"""Translation validation (ISSUE 15 tentpole).

Oracle 1: the symbolic executor certifies a hand-built 2-mesh
4-microbatch accumulation plan — every protected output's term graph
equals the serially-composed reference, using exactly the documented
axioms (accumulation reassociation, resharding identity).  Oracle 2:
every mutation class is caught with its named finding and a rendered
term-diff witness — swapped same-shape operands
(equiv.output-mismatch), a dropped microbatch contribution
(equiv.dropped-microbatch), a duplicated accumulation edge
(equiv.duplicated-accumulation), a read of a donated slot after its
update consumed it (equiv.stale-operand) — and the severities route
through ``verify_model``'s merged verdict.  Oracle 3: the committed
fixture matches the in-test generator byte for byte, certifies
deterministically (the perf gate pins its exact term count), and
``verify_tool.py equiv`` emits the stable ``alpa-equiv/v1`` schema.
Oracle 4: on a real 2-mesh pipeline the default knobs prove every
protected output with zero ``equiv.*`` findings,
``verify_plans_equiv="error"`` blocks the launch of a tampered
reference independently of ``verify_plans``, warm restarts replay the
byte-identical cached verdict, and ``equiv.txt`` lands in the debug
dump.
"""
import dataclasses
import json
import os
import subprocess
import sys

import pytest

import alpa_tpu
from alpa_tpu import PipeshardParallel
from alpa_tpu.analysis import equivalence as eq
from alpa_tpu.analysis import model_check as mc
from alpa_tpu.analysis import plan_verifier as pv
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
FIXTURE = os.path.join(REPO, "benchmark", "results",
                       "equiv_fixture_plan.json")


@pytest.fixture(autouse=True)
def _restore_globals():
    prev = (global_config.pipeline_dispatch_mode,
            global_config.verify_plans,
            global_config.verify_plans_equiv,
            global_config.equiv_term_budget,
            global_config.compile_cache_dir)
    yield
    (global_config.pipeline_dispatch_mode,
     global_config.verify_plans,
     global_config.verify_plans_equiv,
     global_config.equiv_term_budget,
     global_config.compile_cache_dir) = prev
    from alpa_tpu.compile_cache import reset_compile_cache
    reset_compile_cache()


def _compile_pipeline(num_stages=2, mode="registers"):
    alpa_tpu.init("local")
    global_config.pipeline_dispatch_mode = mode
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=4),
        stage_option=UniformStageOption(num_stages=num_stages))
    step = get_mlp_train_step(method, use_value_and_grad=False)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)
    state, _ = step(state, batch)
    return step.get_last_executable(), state, batch, step


# ---------------------------------------------------------------------
# the hand-built 2-mesh 4-microbatch plan (== the committed fixture)
# ---------------------------------------------------------------------
#
# Shape: stage0 on mesh 0 maps each microbatch x -> h; h reshards to
# mesh 1; stage1 accumulates gradient contributions in place (slot 15,
# donated accumulator); apply consumes the summed gradient and the
# donated weight into the protected updated weight (slot 16).

_S0 = "stage0#fix0seed"
_S1 = "stage1#fix1seed"
_AP = "apply#fixapseed"
N_MB = 4
_F32 = "float32"
_AVAL = ((4, 4), _F32)
_PREC = {"n_matmul": 1, "n_reduce": 0, "n_cast": 0,
         "min_accum": "float32", "below_fp32_accum": False}


def _fixture_slots():
    s = {}
    for mb in range(N_MB):
        s[mb] = pv.SlotModel(mb, "x", mb, 0, (4, 4), _F32, 64,
                             preplaced=True, provenance="activation")
    s[4] = pv.SlotModel(4, "w0", -1, 0, (4, 4), _F32, 64,
                        preplaced=True, provenance="param")
    for mb in range(N_MB):
        s[5 + mb] = pv.SlotModel(5 + mb, "h", mb, 0, (4, 4), _F32, 64)
        s[9 + mb] = pv.SlotModel(9 + mb, "h", mb, 1, (4, 4), _F32, 64)
    s[13] = pv.SlotModel(13, "w1", -1, 1, (4, 4), _F32, 64,
                         preplaced=True, provenance="param")
    s[14] = pv.SlotModel(14, "g1", -1, 1, (4, 4), _F32, 64,
                         preplaced=True, provenance="gradient")
    s[15] = pv.SlotModel(15, "gsum", -1, 1, (4, 4), _F32, 64,
                         protected=True, provenance="gradient")
    s[16] = pv.SlotModel(16, "w1_new", -1, 1, (4, 4), _F32, 64,
                         protected=True, provenance="param")
    return s


def _fixture_ops():
    ops = []
    for mb in range(N_MB):
        b = 5 * mb
        ops.append(pv.OpModel(
            b + 0, "RUN", 0, reads=(mb, 4), writes=(5 + mb,),
            in_avals=(_AVAL, _AVAL), out_avals=(_AVAL,),
            precision=dict(_PREC),
            equiv={"stage": _S0, "mb": mb, "donate": [], "acc": {}},
            label=f"RUN stage0 mb{mb}"))
        # the RESHARD lives on the destination stream (its RECV half);
        # the model checker interleaves the SEND into the source stream
        ops.append(pv.OpModel(
            b + 1, "RESHARD", 1, reads=(5 + mb,), writes=(9 + mb,),
            edge=(0, 1), cross=True, nbytes=64,
            label=f"RESHARD h mb{mb} 0->1"))
        acc_slot = 14 if mb == 0 else 15
        ops.append(pv.OpModel(
            b + 2, "RUN", 1, reads=(9 + mb, 13, acc_slot),
            writes=(15,), kills=(acc_slot,),
            in_avals=(_AVAL, _AVAL, _AVAL), out_avals=(_AVAL,),
            precision=dict(_PREC),
            equiv={"stage": _S1, "mb": mb, "donate": [2],
                   "acc": {"0": 2}},
            label=f"RUN stage1 mb{mb}"))
        ops.append(pv.OpModel(b + 3, "FREE", 0, kills=(5 + mb,),
                              label=f"FREE h@m0 mb{mb}"))
        ops.append(pv.OpModel(b + 4, "FREE", 1, kills=(9 + mb,),
                              label=f"FREE h@m1 mb{mb}"))
    ops.append(pv.OpModel(
        5 * N_MB, "RUN", 1, reads=(15, 13), writes=(16,), kills=(13,),
        in_avals=(_AVAL, _AVAL), out_avals=(_AVAL,),
        precision=dict(_PREC),
        equiv={"stage": _AP, "mb": -1, "donate": [1], "acc": {}},
        label="RUN apply"))
    return ops


def _fixture_reference():
    apps = []
    for mb in range(N_MB):
        apps.append({"stage": _S0, "mb": mb, "donate": [], "acc": {},
                     "in": [["x", mb], ["w0", -1]],
                     "out": [["h", mb]]})
    for mb in range(N_MB):
        apps.append({"stage": _S1, "mb": mb, "donate": [2],
                     "acc": {"0": 2},
                     "in": [["h", mb], ["w1", -1],
                            ["g1" if mb == 0 else "gsum", -1]],
                     "out": [["gsum", -1]]})
    apps.append({"stage": _AP, "mb": -1, "donate": [1], "acc": {},
                 "in": [["gsum", -1], ["w1", -1]],
                 "out": [["w1_new", -1]]})
    return {"format": "alpa-equiv-reference/v1", "apps": apps,
            "num_microbatches": N_MB}


def _fixture_model(ops=None):
    streams = [[], []]
    ops = ops if ops is not None else _fixture_ops()
    for op in ops:
        streams[op.mesh].append(op.idx)
    deps = {}
    for mb in range(N_MB):
        b = 5 * mb
        deps[b + 1] = {b + 0}       # SEND waits for the h producer
        deps[b + 3] = {b + 1}       # FREE h@m0 waits for the SEND
    return pv.PlanModel(
        ops=ops, slots=_fixture_slots(), num_meshes=2,
        streams=streams, deps=deps, reference=_fixture_reference())


def _codes(res):
    return [f.code for f in res.findings]


# ---------------------------------------------------------------------
# oracle 1: the clean plan proves
# ---------------------------------------------------------------------

def test_clean_plan_proves_every_protected_output():
    res = eq.check_equiv(_fixture_model())
    assert res.ok and not res.findings, res.format()
    st = res.stats
    assert st["n_outputs"] == 2 and st["n_proved"] == 2
    assert st["num_microbatches"] == N_MB
    assert st["n_apps"] == 2 * N_MB + 1
    assert st["axioms_used"] == [eq.AXIOM_ACC, eq.AXIOM_RESHARD]
    assert not st["partial"]
    by_var = {r["var"]: r for r in st["per_output"]}
    assert by_var["gsum"]["status"] == "proved"
    assert by_var["w1_new"]["status"] == "proved"
    # the accumulated output's proof used both axioms
    assert by_var["gsum"]["axioms"] == \
        [eq.AXIOM_ACC, eq.AXIOM_RESHARD]


def test_sum_terms_are_order_insensitive_by_construction():
    """Reassociation/commutation is baked into term identity: any
    member order and nesting of the same multiset interns to one id."""
    t = eq.TermTable()
    a, b, c = (t.leaf(v, 0) for v in "abc")
    assert t.sum_((a, t.sum_((b, c)))) == t.sum_((t.sum_((c, a)), b))
    # ... but a genuine multiset difference is a different term
    assert t.sum_((a, b)) != t.sum_((a, b, b))


def test_candidate_schedule_order_does_not_matter():
    """The proof is schedule-independent: reversing the interleaving of
    the two mesh streams (the flat emission order stays topological)
    yields the identical stats."""
    res = eq.check_equiv(_fixture_model())
    model = _fixture_model()
    # drop all FREEs of mesh-0 h slots to the very end: a legal
    # reordering (no op reads them afterwards)
    frees = [op for op in model.ops
             if op.kind == "FREE" and op.mesh == 0]
    rest = [op for op in model.ops
            if not (op.kind == "FREE" and op.mesh == 0)]
    model2 = dataclasses.replace(model, ops=rest + frees)
    res2 = eq.check_equiv(model2)
    assert res2.ok
    assert res2.stats["n_terms"] == res.stats["n_terms"]
    assert res2.stats["n_proved"] == res.stats["n_proved"]


def test_budget_exhaustion_degrades_to_partial_note():
    res = eq.check_equiv(_fixture_model(), budget=5)
    assert res.ok                     # note-severity: partial, not false
    assert _codes(res) == ["equiv.budget-exhausted"]
    assert res.stats["partial"] is True
    assert res.stats["n_terms"] <= 5


# ---------------------------------------------------------------------
# oracle 2: mutation classes
# ---------------------------------------------------------------------

def test_mutation_swapped_operands_is_output_mismatch():
    ops = _fixture_ops()
    # stage0 mb0 reads (x, w0) -> wire them backwards (same shapes,
    # so the typing pass cannot see it; only the proof can)
    ops[0] = dataclasses.replace(ops[0], reads=(4, 0))
    res = eq.check_equiv(_fixture_model(ops))
    assert not res.ok
    assert "equiv.output-mismatch" in _codes(res), res.format()
    f = next(f for f in res.findings
             if f.code == "equiv.output-mismatch")
    assert "reference computes" in f.message \
        and "the plan computes" in f.message
    by_var = {r["var"]: r for r in res.stats["per_output"]}
    assert by_var["gsum"]["status"] == "mismatched"
    assert "witness" in by_var["gsum"]


def test_mutation_dropped_microbatch_is_named():
    ops = _fixture_ops()
    model = _fixture_model(ops)
    # stage1 mb2 accumulates into a scratch slot instead of the real
    # accumulator (and stops donating it): mb2's contribution is lost
    model.slots[17] = pv.SlotModel(17, "scratch", -1, 1, (4, 4), _F32,
                                   64)
    ops[12] = dataclasses.replace(ops[12], reads=(11, 13, 15),
                                  writes=(17,), kills=())
    res = eq.check_equiv(model)
    assert not res.ok
    assert "equiv.dropped-microbatch" in _codes(res), res.format()
    f = next(f for f in res.findings
             if f.code == "equiv.dropped-microbatch")
    assert "missing accumulation member" in f.message
    assert ".mb2(" in f.message       # names the lost contribution


def test_mutation_duplicated_accumulation_is_named():
    ops = _fixture_ops()
    # replace the mb2 h-free with a second mb2 accumulation: the
    # gradient is counted twice
    ops[14] = dataclasses.replace(
        ops[14], kind="RUN", reads=(11, 13, 15), writes=(15,),
        kills=(15,), in_avals=(_AVAL, _AVAL, _AVAL),
        out_avals=(_AVAL,), precision=dict(_PREC),
        equiv={"stage": _S1, "mb": 2, "donate": [2], "acc": {"0": 2}},
        label="RUN stage1 mb2 (dup)")
    res = eq.check_equiv(_fixture_model(ops))
    assert not res.ok
    assert "equiv.duplicated-accumulation" in _codes(res), res.format()
    f = next(f for f in res.findings
             if f.code == "equiv.duplicated-accumulation")
    assert "surplus accumulation member" in f.message
    assert ".mb2(" in f.message


def test_mutation_read_after_donation_is_stale_operand():
    ops = _fixture_ops()
    # stage1 mb1 reads the *initial* accumulator slot — consumed by
    # mb0's donating update — instead of the live running sum
    ops[7] = dataclasses.replace(ops[7], reads=(10, 13, 14),
                                 kills=(14,))
    res = eq.check_equiv(_fixture_model(ops))
    assert not res.ok
    [f] = [f for f in res.findings
           if f.code == "equiv.stale-operand"]
    assert f.op == 7
    assert "consumed at op 2" in f.message
    # downstream outputs are poisoned, not double-reported
    by_var = {r["var"]: r for r in res.stats["per_output"]}
    assert by_var["gsum"]["status"] == "stale"
    assert by_var["w1_new"]["status"] == "stale"
    assert _codes(res) == ["equiv.stale-operand"]


def test_quant_axiom_without_certificate_is_unproven_output():
    """A quantized hop is identity-within-bound — admissible only when
    the numerics certificate backs it; without one the proof degrades
    to the warning-severity unproven finding."""
    ops = _fixture_ops()
    ops[1] = dataclasses.replace(ops[1], strategy="quantized",
                                 codec="int8", groupable=False)
    res = eq.check_equiv(_fixture_model(ops), numerics_ok=True)
    assert res.ok and not res.findings, res.format()
    assert eq.AXIOM_QUANT in res.stats["axioms_used"]
    res2 = eq.check_equiv(_fixture_model(ops), numerics_ok=None)
    assert res2.ok                    # warning-class, not error
    assert "equiv.unproven-output" in _codes(res2), res2.format()
    by_var = {r["var"]: r for r in res2.stats["per_output"]}
    assert by_var["gsum"]["status"] == "unproven"


def test_verify_model_merges_equiv_severities():
    ops = _fixture_ops()
    ops[0] = dataclasses.replace(ops[0], reads=(4, 0))
    verdict = pv.verify_model(_fixture_model(ops), equiv=True)
    assert not verdict.ok
    assert "equiv.output-mismatch" in {f.code for f in verdict.errors}
    assert verdict.stats["equiv"]["n_proved"] < 2
    # ... and equiv=False leaves the verdict equivalence-free
    clean = pv.verify_model(_fixture_model(), equiv=False)
    assert "equiv" not in clean.stats
    assert not [f for f in clean.findings()
                if f.code.startswith("equiv.")]


# ---------------------------------------------------------------------
# oracle 3: committed fixture, perf gate, tooling schema
# ---------------------------------------------------------------------

def test_committed_fixture_matches_generator():
    """The committed JSON is exactly what the in-test builder
    serializes to — regenerate with
    ``json.dump(mc.model_to_dict(_fixture_model()), ..., indent=1,
    sort_keys=True)`` when the plan shape changes."""
    with open(FIXTURE, encoding="utf-8") as f:
        committed = json.load(f)
    generated = json.loads(json.dumps(mc.model_to_dict(
        _fixture_model())))     # tuples -> lists, like the file
    assert committed == generated


def test_fixture_certifies_and_perf_gate_pins_it():
    model, hooks, _ = mc.load_fixture(FIXTURE)
    res = eq.check_equiv(model, hooks=hooks)
    assert res.ok and not res.findings, res.format()
    assert res.stats["n_proved"] == 2
    # hash-consing is deterministic: the exact term count is pinned
    assert res.stats["n_terms"] == 20
    # the full seven-analysis verdict is clean (the fixture is a real,
    # well-formed plan, not just an equivalence prop)
    verdict = pv.verify_model(model, hooks=hooks, model_check=True,
                              numerics=True, equiv=True)
    assert verdict.ok and not verdict.warnings, verdict.format_table()
    from benchmark.perf_gate import gate
    gv = gate({
        "equiv.terms": float(res.stats["n_terms"]),
        "equiv.seconds": float(res.stats["seconds"]),
    })
    checked = {c["metric"] for c in gv["checks"]}
    assert {"equiv.terms", "equiv.seconds"} <= checked
    assert gv["pass"], gv


def test_fixture_roundtrips_with_reference():
    model, hooks, window = mc.load_fixture(FIXTURE)
    assert model.reference is not None
    assert model.reference["format"] == "alpa-equiv-reference/v1"
    d = mc.model_to_dict(model, hooks=hooks, overlap_window=window)
    model2, _, _ = mc.model_from_dict(d)
    assert model2.reference == model.reference
    assert eq.reference_digest(model2.reference) == \
        eq.reference_digest(model.reference)


def test_export_metrics_counts_and_sets_gauge():
    res = eq.check_equiv(_fixture_model())
    before = eq._EQUIV_TOTAL.labels("ok").value
    eq._TERMS_TOTAL.set(0.0)
    eq.export_metrics(res.stats, "ok")
    assert eq._EQUIV_TOTAL.labels("ok").value == before + 1
    assert eq._TERMS_TOTAL.value == float(res.stats["n_terms"])
    # SET (not inc): a replay exports the identical gauge value
    eq.export_metrics(res.stats, "ok")
    assert eq._TERMS_TOTAL.value == float(res.stats["n_terms"])
    # a skipped run leaves the gauge untouched
    eq.export_metrics(None, "skipped")
    assert eq._TERMS_TOTAL.value == float(res.stats["n_terms"])


def test_verify_tool_equiv_schema_and_exit_status(tmp_path):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join("scripts", "verify_tool.py"),
         "equiv", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, check=False)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["schema"] == "alpa-equiv/v1"
    assert doc["ok"] is True and doc["findings"] == []
    assert doc["stats"]["n_proved"] == 2
    assert doc["stats"]["n_terms"] == 20
    # a mutated fixture flips ok, names the finding, and exits 1
    with open(FIXTURE, encoding="utf-8") as f:
        d = json.load(f)
    [op0] = [o for o in d["ops"] if o["idx"] == 0]
    op0["reads"] = [4, 0]
    bad = tmp_path / "bad_fixture.json"
    bad.write_text(json.dumps(d))
    out = subprocess.run(
        [sys.executable, os.path.join("scripts", "verify_tool.py"),
         "equiv", "--fixture", str(bad), "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, check=False)
    assert out.returncode == 1, out.stderr
    doc = json.loads(out.stdout)
    assert doc["ok"] is False
    codes = {f["code"] for f in doc["findings"]}
    assert "equiv.output-mismatch" in codes
    assert all(f["severity"] == "error" for f in doc["findings"]
               if f["code"] == "equiv.output-mismatch")


# ---------------------------------------------------------------------
# oracle 4: real 2-mesh pipeline end to end
# ---------------------------------------------------------------------

def test_default_knobs_prove_real_pipeline_outputs():
    """Default verify_plans_equiv='warn': the validation runs at
    lowering time, proves every protected output of the real 2-stage
    MLP pipeline, and raises zero equiv.* findings."""
    ex, *_ = _compile_pipeline(num_stages=2)
    verdict = ex._register_programs["registers"].verdict
    assert verdict is not None and verdict.ok
    st = verdict.stats["equiv"]
    assert st["n_outputs"] > 0
    assert st["n_proved"] == st["n_outputs"], st
    assert not st["partial"]
    assert eq.AXIOM_ACC in st["axioms_used"]
    assert not [f for f in verdict.findings()
                if f.code.startswith("equiv.")]


def test_equiv_off_skips_analysis_entirely():
    global_config.verify_plans_equiv = "off"
    ex, *_ = _compile_pipeline(num_stages=2)
    verdict = ex._register_programs["registers"].verdict
    assert verdict is not None and verdict.ok
    assert "equiv" not in verdict.stats


def test_tampered_reference_blocks_launch_in_error_mode(monkeypatch):
    """A lowering that no longer matches its reference decomposition
    must not launch under verify_plans_equiv='error' — independently of
    verify_plans (left at 'warn').  The tampered reference hashes to a
    different cache key, so the cached clean verdict cannot mask it."""
    ex, state, batch, step = _compile_pipeline(num_stages=2)
    orig = eq.build_reference

    def tampered(instructions, num_microbatches=0):
        ref = orig(instructions, num_microbatches)
        ref["apps"] = ref["apps"][:-1]    # drop the last stage app
        return ref

    monkeypatch.setattr(eq, "build_reference", tampered)
    global_config.verify_plans_equiv = "error"
    assert global_config.verify_plans == "warn"
    ex._register_programs = {}
    ex._register_program = None
    try:
        with pytest.raises(pv.PlanVerificationError) as exc_info:
            step(state, batch)
        assert "translation validation failed" in str(exc_info.value)
        assert "equiv." in str(exc_info.value)
    finally:
        ex._register_programs = {}
        ex._register_program = None


def test_warm_restart_replays_byte_identical_verdict(tmp_path):
    from alpa_tpu.compile_cache import (get_compile_cache,
                                        reset_compile_cache)
    global_config.compile_cache_dir = str(tmp_path)
    reset_compile_cache()
    ex, *_ = _compile_pipeline(num_stages=2)
    cold = ex._register_programs["registers"].verdict
    assert cold.stats["equiv"]["n_proved"] > 0, cold.stats
    # warm restart: wipe the lowering and the in-memory tier
    reset_compile_cache()
    ex._register_programs = {}
    ex._register_program = None
    eq._TERMS_TOTAL.set(0.0)
    ex._ensure_lowered("registers")
    warm = ex._register_programs["registers"].verdict
    assert warm.to_dict() == cold.to_dict()
    assert json.dumps(warm.to_dict(), sort_keys=True, default=str) == \
        json.dumps(cold.to_dict(), sort_keys=True, default=str)
    # the cache-hit path re-exports the terms gauge from replayed stats
    assert eq._TERMS_TOTAL.value == \
        float(cold.stats["equiv"]["n_terms"])
    stats = get_compile_cache().stats()["namespaces"]["plan_verdict"]
    assert stats["hits"] >= 1, stats


def test_equiv_txt_in_debug_dump(tmp_path):
    from alpa_tpu.monitoring import dump_debug_info
    ex, *_ = _compile_pipeline(num_stages=2)
    dump_debug_info(ex, str(tmp_path))
    path = tmp_path / "equiv.txt"
    assert path.exists()
    text = path.read_text()
    assert "translation validation" in text
    assert "proved equivalent to the source jaxpr" in text
    assert "per-output proofs:" in text
