"""Pipeshard end-to-end correctness (ref PipelineBasicTest, testing.py:233).

Oracle: PipeshardParallel == serial numerics across schedules, microbatch
counts, manual/auto layers, and models.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu import PipeshardParallel, get_3d_parallel_method
from alpa_tpu.pipeline_parallel.layer_construction import (AutoLayerOption,
                                                           ManualLayerOption)
from alpa_tpu.pipeline_parallel.stage_construction import (ManualStageOption,
                                                           UniformStageOption)
from alpa_tpu.testing import (assert_allclose, create_mlp_train_state_and_batch,
                              get_mlp_train_step, skip_if_old_jax)


def _compare_pipeshard(method, n_steps=2, rtol=2e-3, num_layers=4,
                       manual=True):
    alpa_tpu.init(cluster="local")
    state_p, batch = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=num_layers, manual_pipeline_layer=manual)
    state_s, _ = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=num_layers, manual_pipeline_layer=manual)
    pstep = get_mlp_train_step(method, use_value_and_grad=True)
    serial = get_mlp_train_step(None)
    for _ in range(n_steps):
        state_p, loss_p = pstep(state_p, batch)
        state_s, loss_s = serial(state_s, batch)
    assert_allclose(float(loss_s), float(loss_p), rtol, rtol)
    assert_allclose(jax.device_get(state_s.params),
                    jax.device_get(state_p.params), rtol, rtol)
    return pstep.get_last_executable()


class TestPipeshard:

    def test_1f1b_manual_layers(self):
        ex = _compare_pipeshard(
            PipeshardParallel(num_micro_batches=2,
                              layer_option=ManualLayerOption(),
                              stage_option=UniformStageOption(num_stages=2),
                              pipeline_schedule="1f1b"))
        assert ex.num_meshes == 2

    def test_gpipe(self):
        _compare_pipeshard(
            PipeshardParallel(num_micro_batches=4,
                              layer_option=ManualLayerOption(),
                              stage_option=UniformStageOption(num_stages=2),
                              pipeline_schedule="gpipe"))

    def test_1f1b_overlap_friendly(self):
        _compare_pipeshard(
            PipeshardParallel(num_micro_batches=4,
                              layer_option=ManualLayerOption(),
                              stage_option=UniformStageOption(num_stages=2),
                              pipeline_schedule="1f1b_overlap_friendly"))

    @skip_if_old_jax("XLA INTERNAL error compiling auto-layer stages: "
                     "donated-input aliasing pairs sub-shapes of different "
                     "sizes under microbatched accumulation")
    def test_auto_layers(self):
        _compare_pipeshard(
            PipeshardParallel(num_micro_batches=2,
                              layer_option=AutoLayerOption(layer_num=2),
                              stage_option=UniformStageOption(num_stages=2)),
            manual=False)

    def test_single_microbatch(self):
        _compare_pipeshard(
            PipeshardParallel(num_micro_batches=1,
                              layer_option=ManualLayerOption(),
                              stage_option=UniformStageOption(num_stages=2)))

    @skip_if_old_jax("XLA INTERNAL error compiling auto-layer stages: "
                     "donated-input aliasing pairs sub-shapes of different "
                     "sizes under microbatched accumulation")
    def test_four_stages(self):
        _compare_pipeshard(
            PipeshardParallel(num_micro_batches=2,
                              layer_option=AutoLayerOption(layer_num=4),
                              stage_option=UniformStageOption(num_stages=4)),
            num_layers=8, manual=False)

    def test_3d_parallel_method(self):
        alpa_tpu.init(cluster="local")
        method = get_3d_parallel_method(num_micro_batches=2,
                                        data_parallel=2,
                                        operator_parallel=2,
                                        pipeline_parallel=2)
        _compare_pipeshard(method)

    def test_remat_layers(self):
        _compare_pipeshard(
            PipeshardParallel(num_micro_batches=2,
                              layer_option=ManualLayerOption(
                                  remat_layer=True),
                              stage_option=UniformStageOption(num_stages=2)))

    def test_global_norm_clipping_falls_back_single_mesh_apply(self):
        """clip_by_global_norm creates a cyclic apply partition; the driver
        must fall back to single-mesh apply and stay correct."""
        import optax
        from flax.training import train_state

        from alpa_tpu.testing import MLPModel

        alpa_tpu.init(cluster="local")
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (64, 32))
        y = jax.random.normal(rng, (64, 32))
        model = MLPModel(hidden_dim=32, output_dim=32, num_layers=4,
                         manual_pipeline_layer=True)
        tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adam(1e-3))

        def mkstate():
            return train_state.TrainState.create(apply_fn=model.apply,
                                                 params=model.init(rng, x),
                                                 tx=tx)

        def step(state, batch):

            def loss_fn(p):
                out = state.apply_fn(p, batch["x"])
                return jnp.mean((out - batch["y"])**2)

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), loss

        batch = {"x": x, "y": y}
        method = PipeshardParallel(num_micro_batches=2,
                                   layer_option=ManualLayerOption(),
                                   stage_option=UniformStageOption(
                                       num_stages=2))
        pstep = alpa_tpu.parallelize(step, method=method)
        serial = jax.jit(step)
        sp, ssr = mkstate(), mkstate()
        for _ in range(2):
            sp, lp = pstep(sp, batch)
            ssr, ls = serial(ssr, batch)
        assert_allclose(float(ls), float(lp), 1e-3, 1e-3)
        assert_allclose(jax.device_get(ssr.params),
                        jax.device_get(sp.params), 2e-3, 2e-3)

    def test_executable_introspection(self):
        ex = _compare_pipeshard(
            PipeshardParallel(num_micro_batches=2,
                              layer_option=ManualLayerOption(),
                              stage_option=UniformStageOption(num_stages=2)))
        assert "HloModule" in ex.get_hlo_text()
        assert "b0s0" in ex.get_schedule_text()
        assert "RUN" in ex.get_instruction_text()


class TestPipeshardGPT:

    @pytest.mark.slow
    def test_gpt_pipeline(self):
        import optax
        from flax.training import train_state

        from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
        from alpa_tpu.model.model_util import cross_entropy_loss

        alpa_tpu.init(cluster="local")
        config = GPTConfig(hidden_size=32, num_layers=4, num_heads=4,
                           seq_len=32, vocab_size=64,
                           pipeline_boundary_every=2)
        model = GPTModel(config)
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (8, 32), 0, 64)
        labels = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 64)

        def make_state():
            params = model.init(rng, ids)
            return train_state.TrainState.create(
                apply_fn=model.apply, params=params, tx=optax.adam(1e-3))

        def train_step_fn(state, batch):

            def loss_fn(p):
                logits = state.apply_fn(p, batch["ids"])
                return cross_entropy_loss(logits.astype(jnp.float32),
                                          batch["labels"])

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), loss

        batch = {"ids": ids, "labels": labels}
        method = PipeshardParallel(num_micro_batches=2,
                                   layer_option=ManualLayerOption(),
                                   stage_option=UniformStageOption(
                                       num_stages=2))
        pstep = alpa_tpu.parallelize(train_step_fn, method=method)
        serial = jax.jit(train_step_fn)
        sp, ss = make_state(), make_state()
        for _ in range(2):
            sp, lp = pstep(sp, batch)
            ss, ls = serial(ss, batch)
        assert_allclose(float(ls), float(lp), 2e-3, 2e-3)
        assert_allclose(jax.device_get(ss.params),
                        jax.device_get(sp.params), 5e-3, 5e-3)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestPipeshardInference:

    def test_pipelined_forward_only(self):
        from alpa_tpu.testing import create_mlp_train_state_and_batch

        alpa_tpu.init(cluster="local")
        state, batch = create_mlp_train_state_and_batch(batch_size=64,
                                                        num_layers=4)

        @alpa_tpu.parallelize(method=PipeshardParallel(
            num_micro_batches=2,
            layer_option=AutoLayerOption(layer_num=2),
            stage_option=UniformStageOption(num_stages=2),
            pipeline_schedule="inference"), batch_argnums=(1,))
        def forward(state, batch):
            return state.apply_fn(state.params, batch["x"])

        out = forward(state, batch)
        ref = state.apply_fn(state.params, batch["x"])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=1e-5)

    def test_auto_stage_inference_objective(self):
        """Forward-only pipelines use the inference DP objective
        (minimize max stage cost; ref inference_dp,
        stage_construction.py:403) and stay numerically correct."""
        from alpa_tpu.pipeline_parallel.stage_construction import (
            AutoStageOption)
        from alpa_tpu.testing import create_mlp_train_state_and_batch

        alpa_tpu.init(cluster="local")
        state, batch = create_mlp_train_state_and_batch(batch_size=64,
                                                        num_layers=4)

        @alpa_tpu.parallelize(method=PipeshardParallel(
            num_micro_batches=2,
            layer_option=AutoLayerOption(layer_num=4),
            stage_option=AutoStageOption(),
            pipeline_schedule="inference"), batch_argnums=(1,))
        def forward(state, batch):
            return state.apply_fn(state.params, batch["x"])

        out = forward(state, batch)
        ref = state.apply_fn(state.params, batch["x"])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=1e-5)

    def test_scalar_output_with_microbatching_errors(self):
        from alpa_tpu.testing import create_mlp_train_state_and_batch

        alpa_tpu.init(cluster="local")
        state, batch = create_mlp_train_state_and_batch(batch_size=64,
                                                        num_layers=4)

        @alpa_tpu.parallelize(method=PipeshardParallel(
            num_micro_batches=2,
            layer_option=AutoLayerOption(layer_num=2),
            stage_option=UniformStageOption(num_stages=2),
            pipeline_schedule="inference"), batch_argnums=(1,))
        def mean_out(state, batch):
            return jnp.mean(state.apply_fn(state.params, batch["x"]))

        with pytest.raises(ValueError, match="scalar output"):
            mean_out(state, batch)


class TestFourStageGPT:

    def test_four_stages_marker_passthrough(self):
        """Regression: a value passing through a layer's start AND end
        marker untouched (common in >2-stage transformers: cotangents and
        residuals riding through middle layers) must stay connected —
        the slicer emits an identity eqn for passthrough pairs.  Before
        the fix this raised KeyError at stage compile (phantom outvar)."""
        import optax
        from flax.training import train_state

        from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
        from alpa_tpu.model.model_util import cross_entropy_loss

        alpa_tpu.init(cluster="local")
        cfg = GPTConfig(hidden_size=64, num_layers=4, num_heads=4,
                        seq_len=32, vocab_size=128)
        model = GPTModel(cfg)
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (8, 32), 0, 128)
        labels = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, 128)
        params = model.init(rng, ids)
        state = train_state.TrainState.create(apply_fn=model.apply,
                                              params=params,
                                              tx=optax.adam(1e-3))
        batch = {"ids": ids, "labels": labels}

        def step_fn(parallel):
            def train_step(state, batch):
                loss, grads = alpa_tpu.value_and_grad(
                    lambda p: cross_entropy_loss(
                        state.apply_fn(p, batch["ids"]).astype(jnp.float32),
                        batch["labels"]))(state.params)
                return state.apply_gradients(grads=grads), loss
            if parallel:
                return alpa_tpu.parallelize(
                    train_step,
                    method=PipeshardParallel(
                        num_micro_batches=2,
                        layer_option=AutoLayerOption(layer_num=4),
                        stage_option=UniformStageOption(num_stages=4)))
            return jax.jit(lambda s, b: (
                s.apply_gradients(grads=jax.grad(
                    lambda p: cross_entropy_loss(
                        s.apply_fn(p, b["ids"]).astype(jnp.float32),
                        b["labels"]))(s.params)),
                cross_entropy_loss(
                    s.apply_fn(s.params, b["ids"]).astype(jnp.float32),
                    b["labels"])))

        # serial first: the parallel step donates the state buffers
        state_s, loss_s = step_fn(False)(state, batch)
        state_p, loss_p = step_fn(True)(state, batch)
        assert_allclose(float(loss_s), float(loss_p), 2e-3, 2e-3)
        assert_allclose(jax.device_get(state_s.params),
                        jax.device_get(state_p.params), 2e-3, 2e-3)


class TestBertPipeshard:

    def test_bert_pretraining_pipelined(self):
        """BERT MLM+NSP pretraining through auto-layer pipeshard matches
        serial numerics (params to 2e-3; the loss VALUE differs slightly
        because the weighted-MLM mean normalizes per microbatch — the
        same microbatch-mean semantics as the reference's
        apply_grad_get_mean rewrite)."""
        import optax
        from flax.training import train_state

        from alpa_tpu.model.bert_model import (BertConfig,
                                               BertForPreTraining,
                                               bert_pretraining_loss)

        alpa_tpu.init(cluster="local")
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_layers=4,
                         num_heads=4, seq_len=16)
        model = BertForPreTraining(cfg)
        rng = jax.random.PRNGKey(0)
        ids = jax.random.randint(rng, (8, 16), 0, 64)
        params = model.init(rng, ids)
        state = train_state.TrainState.create(apply_fn=model.apply,
                                              params=params,
                                              tx=optax.sgd(1e-2))
        batch = {
            "ids": ids,
            "mlm_labels": jax.random.randint(jax.random.PRNGKey(1),
                                             (8, 16), 0, 64),
            "mlm_w": (jax.random.uniform(jax.random.PRNGKey(2),
                                         (8, 16)) < 0.15).astype(
                                             jnp.float32),
            "nsp": jax.random.randint(jax.random.PRNGKey(3), (8,), 0, 2),
        }

        def make_step(parallel):
            def train_step(state, batch):
                def loss_fn(p):
                    ml, nl = state.apply_fn(p, batch["ids"])
                    return bert_pretraining_loss(
                        ml, nl, batch["mlm_labels"], batch["mlm_w"],
                        batch["nsp"])
                vg = (alpa_tpu.value_and_grad if parallel else
                      jax.value_and_grad)
                loss, grads = vg(loss_fn)(state.params)
                return state.apply_gradients(grads=grads), loss
            if parallel:
                return alpa_tpu.parallelize(
                    train_step,
                    method=PipeshardParallel(
                        num_micro_batches=2,
                        layer_option=AutoLayerOption(layer_num=2),
                        stage_option=UniformStageOption(num_stages=2)),
                    donate_argnums=())
            return jax.jit(train_step)

        s_s, l_s = make_step(False)(state, batch)
        s_p, l_p = make_step(True)(state, batch)
        assert_allclose(float(l_s), float(l_p), 2e-2, 2e-2)
        assert_allclose(jax.device_get(s_s.params),
                        jax.device_get(s_p.params), 2e-3, 2e-3)


class TestAutoStage:

    def test_auto_stage_construction(self):
        """OSDI'22-style auto path: auto layers -> cost model -> native DP
        -> heterogeneous submeshes -> pipeshard runtime == serial."""
        from alpa_tpu.pipeline_parallel.stage_construction import (
            AutoStageOption)
        ex = _compare_pipeshard(
            PipeshardParallel(num_micro_batches=4,
                              layer_option=AutoLayerOption(layer_num=4),
                              stage_option=AutoStageOption(),
                              pipeline_schedule="1f1b"),
            num_layers=8, manual=False)
        assert ex.num_meshes >= 1

    def test_profiling_db_shifts_stage_decisions(self, tmp_path):
        """Auto-stage decisions trace to the profiling DB: a comm-bound
        calibration (measured collectives slow, matmuls fast) must pick a
        different partition than a compute-bound one (VERDICT r1 #2)."""
        from alpa_tpu.mesh_profiling import (MeshProfilingResult,
                                             ProfilingResultDatabase)
        from alpa_tpu.pipeline_parallel.stage_construction import (
            AutoStageOption)

        def make_db(path, sec_per_flop, sec_per_byte):
            res = MeshProfilingResult()
            for flops in (1e6, 1e9, 1e12):
                res.record("dot", ("f32",), flops, flops * sec_per_flop)
            for kind in ("all_reduce", "all_gather", "reduce_scatter",
                         "all_to_all"):
                for nbytes in (1e4, 1e6, 1e8):
                    res.record(kind, ("f32", 8), nbytes,
                               nbytes * sec_per_byte)
            db = ProfilingResultDatabase()
            db.update_one_mesh("1x8-test", res)
            db.save(str(path))
            return str(path)

        # comm-bound: collectives at 1 KB/s, matmuls at 1 PFLOPS
        slow_comm = make_db(tmp_path / "slow_comm.json", 1e-15, 1e-3)
        # compute-bound: matmuls at 1 MFLOPS, collectives at 1 TB/s
        slow_compute = make_db(tmp_path / "slow_compute.json", 1e-6, 1e-12)

        def n_meshes(db_file):
            ex = _compare_pipeshard(
                PipeshardParallel(
                    num_micro_batches=4,
                    layer_option=AutoLayerOption(layer_num=4),
                    stage_option=AutoStageOption(
                        profiling_database_filename=db_file),
                    pipeline_schedule="1f1b"),
                num_layers=8, manual=False)
            return ex.num_meshes

        comm_bound = n_meshes(slow_comm)
        compute_bound = n_meshes(slow_compute)
        # comm-bound: avoid intra-stage collectives -> many small meshes;
        # compute-bound: parallelize compute -> few large meshes
        assert comm_bound > compute_bound, (comm_bound, compute_bound)

    def test_stage_dp_position_aware_memory(self):
        """1F1B memory feasibility uses the stage's distance from the
        pipeline end (ref max_n_succ_stages, stage_profiling.py:756):
        earlier stages hold more in-flight microbatches, and the C++ and
        Python solvers agree."""
        from alpa_tpu.pipeline_parallel.stage_dp import (_stage_dp_python,
                                                         stage_dp_solve)
        L, M, D, B = 4, 2, 4, 4
        C = np.full((L, L, M), np.inf)
        for i in range(L):
            for j in range(i, L):
                C[i, j, 0] = (j - i + 1) * 1.0
                C[i, j, 1] = (j - i + 1) * 0.6
        mem_p = np.ones((L, L, M))
        mem_a = np.full((L, L, M), 2.0)

        for budget, max_stages in ((0.0, 4), (5.0, 2)):
            native = stage_dp_solve(C, [1, 2], D, B, mem_p, mem_a,
                                    mem_budget=budget)
            python = _stage_dp_python(C, np.array([1, 2]), D, B, mem_p,
                                      mem_a, budget)
            assert native == python, (budget, native, python)
            assert native is not None and len(native) <= max_stages
        # param(1) + 1*act(2) = 3 exceeds 2.9 even for the last stage
        assert stage_dp_solve(C, [1, 2], D, B, mem_p, mem_a,
                              mem_budget=2.9) is None

    def test_stage_dp_inflight_modes(self):
        """Memory feasibility follows the schedule's in-flight profile:
        inference pipelines hold ~1 microbatch per stage regardless of the
        objective's effective B (ADVICE r2: inference_dp must not apply the
        1F1B stacking factor); gpipe stacks all B; overlap-friendly ~2x
        1F1B.  Native and Python solvers agree mode by mode."""
        from alpa_tpu.pipeline_parallel.stage_dp import (_INFLIGHT_MODES,
                                                         _stage_dp_python,
                                                         stage_dp_solve)
        L, M, D, B = 4, 1, 4, 4096
        C = np.full((L, L, M), np.inf)
        for i in range(L):
            for j in range(i, L):
                C[i, j, 0] = (j - i + 1) * 1.0
        mem_p = np.ones((L, L, M))
        mem_a = np.full((L, L, M), 2.0)
        sizes = [1]

        # budget 3: param(1) + 1*act(2) fits only with inflight == 1.
        # 1F1B with B=4096 rejects everything (earliest stage stacks 4);
        # inference accepts the 4-stage partition.
        assert stage_dp_solve(C, sizes, D, B, mem_p, mem_a, mem_budget=3.0,
                              inflight_mode="1f1b") is None
        part = stage_dp_solve(C, sizes, D, B, mem_p, mem_a, mem_budget=3.0,
                              inflight_mode="inference")
        assert part is not None and len(part) == 4

        # gpipe stacks all B microbatches even at small B
        assert stage_dp_solve(C, sizes, D, 4, mem_p, mem_a, mem_budget=5.0,
                              inflight_mode="gpipe") is None
        # with B large enough that the min(., B) cap never binds, the
        # 4-stage pipeline's earliest stage holds 4 under 1f1b (mem 9) but
        # 2*4-1 = 7 under overlap-friendly (mem 15): budget 9 separates them
        assert stage_dp_solve(C, sizes, D, 100, mem_p, mem_a, mem_budget=9.0,
                              inflight_mode="1f1b") is not None
        assert stage_dp_solve(C, sizes, D, 100, mem_p, mem_a, mem_budget=9.0,
                              inflight_mode="1f1b_overlap_friendly") is None

        # native == python for every mode
        for name, mode in _INFLIGHT_MODES.items():
            native = stage_dp_solve(C, sizes, D, 100, mem_p, mem_a,
                                    mem_budget=9.0, inflight_mode=name)
            python = _stage_dp_python(C, np.array(sizes), D, 100, mem_p,
                                      mem_a, 9.0, mode)
            assert native == python, (name, native, python)

    def test_submesh_choice_spaces(self):
        """The search-space argument is live (r2 VERDICT weak #10: the
        cross-host branch ignored it): power_of_two only keeps 2^k host
        counts, all keeps every count, small_power_of_two caps at 4."""
        from alpa_tpu.pipeline_parallel.stage_construction import (
            get_submesh_choices)
        assert get_submesh_choices(8, 4, "power_of_two") == [
            (1, 1), (1, 2), (1, 4), (2, 4), (4, 4), (8, 4)]
        assert get_submesh_choices(6, 4, "all") == [
            (1, 1), (1, 2), (1, 4), (2, 4), (3, 4), (4, 4), (5, 4), (6, 4)]
        assert get_submesh_choices(8, 4, "small_power_of_two") == [
            (1, 1), (1, 2), (1, 4), (2, 4), (4, 4)]
        with pytest.raises(ValueError):
            get_submesh_choices(8, 4, "bogus")

    def test_native_dp_solver_loaded(self):
        import shutil
        if shutil.which("make") is None or shutil.which("g++") is None:
            pytest.skip("no C++ toolchain; Python fallback covers this env")
        from alpa_tpu.pipeline_parallel.stage_dp import _load_native
        assert _load_native() is not None, (
            "C++ stage DP library failed to build/load")


class TestTraceDump:

    def test_chrome_trace_dump(self, tmp_path):
        import json

        from alpa_tpu.global_env import global_config
        from alpa_tpu.telemetry import trace as ttrace

        ttrace.get_recorder().clear()
        global_config.collect_trace = True
        try:
            ex = _compare_pipeshard(
                PipeshardParallel(num_micro_batches=2,
                                  layer_option=ManualLayerOption(),
                                  stage_option=UniformStageOption(
                                      num_stages=2)),
                n_steps=1)
            f = str(tmp_path / "trace.json")
            ex.dump_stage_execution_trace(f)
            with open(f, encoding="utf-8") as fh:
                trace = json.load(fh)
            # instructions are spans named after the instruction text
            # ("RUN stage_0_fwd", "RESHARD 0->1 ...") on the unified
            # telemetry recorder — no more legacy instant markers
            names = {e["name"] for e in trace["traceEvents"]}
            assert any(n.startswith("RUN") for n in names), names
        finally:
            global_config.collect_trace = False
            ttrace.get_recorder().clear()
