"""The committed GPT-6.7B auto-search plan artifact stays reproducible
(VERDICT r2 next #7: the analog of the reference's recorded GPT-39B
solution, ref benchmark/alpa/suite_auto_gpt.py:80-84).

Re-runs the plan-only search under the checked-in CPU profiling DB and
asserts the solution matches benchmark/results/auto_plan_gpt6.7B_8dev.json.
"""
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
ARTIFACT = os.path.join(REPO, "benchmark", "results",
                        "auto_plan_gpt6.7B_8dev.json")
CPU_DB = os.path.join(REPO, "prof_database_cpu8.json")


@pytest.mark.skipif(not os.path.exists(ARTIFACT),
                    reason="no committed plan artifact")
@pytest.mark.slow
def test_gpt67b_plan_stable_under_checked_in_db():
    from benchmark.auto_search_artifact import search_gpt_plan

    with open(ARTIFACT, encoding="utf-8") as f:
        recorded = json.load(f)["checked_in_db"]
    plan = search_gpt_plan("6.7B", profiling_database=CPU_DB)
    assert plan["forward_stage_layer_ids"] == \
        recorded["forward_stage_layer_ids"]
    assert plan["submesh_shapes"] == recorded["submesh_shapes"]
    assert plan["num_micro_batches"] == recorded["num_micro_batches"]


@pytest.mark.skipif(not os.path.exists(ARTIFACT),
                    reason="no committed plan artifact")
def test_recorded_plans_are_structurally_sane():
    with open(ARTIFACT, encoding="utf-8") as f:
        plans = json.load(f)
    for name, plan in plans.items():
        ids = plan["forward_stage_layer_ids"]
        # stages partition the layer range contiguously
        flat = [i for stage in ids for i in stage]
        assert flat == list(range(plan["num_layers"])), (name, ids)
        # submeshes use exactly the cluster's devices
        total = sum(h * d for h, d in plan["submesh_shapes"])
        assert total == plan["n_devices"], (name, plan["submesh_shapes"])
    # the 2-host plan pipelines across the host boundary instead of
    # running cross-host tensor parallelism
    two_host = plans["analytic_v5e_2x8"]
    assert two_host["num_stages"] >= 2
    assert all(h * d <= 8 for h, d in two_host["submesh_shapes"])


POD_ARTIFACT = os.path.join(REPO, "benchmark", "results",
                            "auto_plan_gpt39B_8x8dev.json")


@pytest.mark.skipif(not os.path.exists(POD_ARTIFACT),
                    reason="no committed pod-scale plan artifact")
def test_pod_scale_39b_plan_structurally_sane():
    """The recorded GPT-39B 8x8 solution (the analog of the reference's
    64-GPU recorded plan, suite_auto_gpt.py:80-84): stages partition the
    auto layers, submeshes cover the pod, and pipeline stages respect
    the host boundary (no cross-host TP under the analytic ICI/DCN
    asymmetry)."""
    with open(POD_ARTIFACT, encoding="utf-8") as f:
        plan = json.load(f)["analytic_v5e_8x8"]
    ids = plan["forward_stage_layer_ids"]
    flat = [i for stage in ids for i in stage]
    assert flat == list(range(plan["num_layers"]))
    assert sum(h * d for h, d in plan["submesh_shapes"]) == 64
    assert plan["num_stages"] >= 4
    # no cross-host tensor parallelism: every stage mesh is within-host
    assert all(h == 1 and d <= 8 for h, d in plan["submesh_shapes"])


POD4_ARTIFACT = os.path.join(REPO, "benchmark", "results",
                             "auto_plan_gpt15B_4x8dev.json")


@pytest.mark.skipif(not os.path.exists(POD4_ARTIFACT),
                    reason="no committed 4x8 plan artifact")
def test_15b_4x8_plan_structurally_sane():
    """The recorded GPT-15B 4x8 solution (the reference's published
    32-GPU case is 4 balanced stages x (1,8), suite_auto_gpt.py:75-79;
    the analytic v5e ladder rationally prefers deeper/narrower — see
    test_stage_dp_validation for the measured-like equivalence): stages
    partition the 16 auto layers near-uniformly, submeshes cover all 32
    devices within hosts, and no mega-stage exists."""
    with open(POD4_ARTIFACT, encoding="utf-8") as f:
        plan = json.load(f)["analytic_v5e_4x8"]
    ids = plan["forward_stage_layer_ids"]
    flat = [i for stage in ids for i in stage]
    assert flat == list(range(plan["num_layers"]))
    assert sum(h * d for h, d in plan["submesh_shapes"]) == 32
    assert all(h == 1 and d <= 8 for h, d in plan["submesh_shapes"])
    assert max(len(s) for s in ids) <= 3, ids
