"""Stage-DP validation (VERDICT r4 next #3).

(a) The DP solver (C++ and the Python fallback) is cross-checked against
brute-force enumeration for L<=6: optimal objective, device-exact
partitions, schedule-dependent memory feasibility.
(b) The auto layer clustering is flops-balanced — the round-4 artifacts'
degenerate [7,1]-style splits came from the clustering DP exempting the
LAST layer from the flops budget (layer_construction.py) and breaking
comm ties toward tiny early layers.
(c) Under a V100-like calibration (fast intra-node collectives) the full
search reproduces the reference's published balanced 6.7B solution shape
(2 stages x (1,8), ref benchmark/alpa/suite_auto_gpt.py:71-74).
"""
import itertools

import numpy as np
import pytest

from alpa_tpu.pipeline_parallel.stage_dp import (_INFLIGHT_MODES,
                                                 _inflight_count,
                                                 _stage_dp_python,
                                                 stage_dp_solve)


def _brute_force(C, sizes, D, B, mem_param, mem_act, mem_budget, mode):
    """Enumerate every contiguous partition x submesh assignment."""
    L, _, M = C.shape
    best_obj, best_part = float("inf"), None

    def compositions(l):
        if l == 0:
            yield ()
            return
        for first in range(1, l + 1):
            for rest in compositions(l - first):
                yield (first,) + rest

    for comp in compositions(L):
        S = len(comp)
        starts = np.concatenate([[0], np.cumsum(comp)]).astype(int)
        for meshes in itertools.product(range(M), repeat=S):
            if sum(sizes[m] for m in meshes) != D:
                continue
            ok = True
            costs = []
            for t, m in enumerate(meshes):
                i, j = starts[t], starts[t + 1] - 1
                c = C[i, j, m]
                if not np.isfinite(c):
                    ok = False
                    break
                # position from the end (1-indexed), as the DP counts
                s = S - t
                inflight = _inflight_count(s, B, mode)
                if mem_budget > 0 and mem_param[i, j, m] + \
                        inflight * mem_act[i, j, m] > mem_budget:
                    ok = False
                    break
                costs.append(c)
            if not ok:
                continue
            obj = sum(costs) + (B - 1) * max(costs)
            if obj < best_obj:
                best_obj = obj
                best_part = [(int(starts[t]), int(starts[t + 1]),
                              int(meshes[t])) for t in range(S)]
    return best_obj, best_part


def _objective(part, C, B):
    costs = [C[a, b - 1, m] for a, b, m in part]
    return sum(costs) + (B - 1) * max(costs)


def _check_instance(C, sizes, D, B, mem_param, mem_act, mem_budget, mode,
                    seed):
    mode_code = _INFLIGHT_MODES[mode]
    ref_obj, ref_part = _brute_force(C, np.asarray(sizes), D, B, mem_param,
                                     mem_act, mem_budget, mode_code)
    for solver in ("full", "python"):
        if solver == "python":
            part = _stage_dp_python(
                np.ascontiguousarray(C, np.float64),
                np.asarray(sizes, np.int64), D, B,
                np.ascontiguousarray(mem_param, np.float64),
                np.ascontiguousarray(mem_act, np.float64), mem_budget,
                mode_code)
        else:
            part = stage_dp_solve(C, sizes, D, B, mem_param, mem_act,
                                  mem_budget, mode)
        if ref_part is None:
            assert part is None, (seed, mode, part)
            continue
        assert part is not None, (seed, mode, ref_part)
        # the partition must be structurally valid and device-exact
        assert part[0][0] == 0 and part[-1][1] == C.shape[0]
        assert all(a < b for a, b, _ in part)
        assert sum(sizes[m] for _, _, m in part) == D
        # and objective-optimal (ties in partition are fine)
        obj = _objective(part, C, B)
        assert obj == pytest.approx(ref_obj, rel=1e-9), \
            (seed, mode, part, ref_part, obj, ref_obj)


@pytest.mark.parametrize("mode", ["1f1b", "gpipe", "1f1b_overlap_friendly",
                                  "inference"])
def test_dp_matches_bruteforce_random(mode):
    rng = np.random.RandomState(0)
    sizes = [1, 2, 4]
    D = 4
    for seed in range(25):
        L = int(rng.randint(2, 7))
        B = int(rng.randint(1, 9))
        C = rng.uniform(0.1, 1.0, size=(L, L, len(sizes)))
        # make spans superadditive-ish and mask some infeasible
        for m in range(len(sizes)):
            for i in range(L):
                for j in range(i, L):
                    C[i, j, m] = C[i:j + 1, i:j + 1, m].diagonal().sum()
        C[rng.uniform(size=C.shape) < 0.1] = np.inf
        mem_param = rng.uniform(0.0, 1.0, size=C.shape)
        mem_act = rng.uniform(0.0, 0.5, size=C.shape)
        budget = float(rng.choice([0.0, 1.5, 3.0]))
        _check_instance(C, sizes, D, B, mem_param, mem_act, budget, mode,
                        seed)


def test_dp_memory_budget_positional():
    """A stage near the pipeline end holds fewer in-flight microbatches
    under 1f1b — a partition infeasible for an early stage must remain
    choosable late."""
    L, M = 2, 1
    sizes = [1]
    C = np.full((L, L, M), np.inf)
    C[0, 0, 0] = C[1, 1, 0] = 1.0
    C[0, 1, 0] = 2.0
    mem_param = np.zeros_like(C)
    mem_act = np.ones_like(C)
    # budget 2.5: last stage (s=1, inflight 1) needs 1.0; first of two
    # stages (s=2, inflight min(2,B)=2) needs 2.0 — both fit; but gpipe
    # (inflight B=8) cannot split at all and must also reject the merged
    # single stage (inflight 8 > 2.5)
    part = stage_dp_solve(C, sizes, 1, 8, mem_param, mem_act, 2.5, "1f1b")
    # D=1 forces a single stage: s=1, inflight min(1, 8)=1 -> feasible
    assert part == [(0, 2, 0)]
    part = stage_dp_solve(C, sizes, 1, 8, mem_param, mem_act, 2.5, "gpipe")
    assert part is None


def test_auto_layer_clustering_is_flops_balanced():
    """Cluster a GPT-like loss jaxpr: every cluster must respect the
    (1 + eps) * total / K flops budget INCLUDING the last one (the
    round-4 degenerate artifacts put 26 of 32 layers in the final
    cluster), and the comm-tie balance term should keep the split near
    uniform."""
    import jax
    import jax.numpy as jnp

    from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
    from alpa_tpu.model.model_util import gpt_lm_loss
    from alpa_tpu.pipeline_parallel.layer_construction import (
        _make_jaxpr_with_tree, cluster_eqns_by_cost)
    from alpa_tpu.util import jaxpr_eqn_flops

    cfg = GPTConfig(hidden_size=64, num_layers=8, num_heads=4, seq_len=64,
                    vocab_size=512, dtype=jnp.float32)
    model = GPTModel(cfg)
    rng = jax.random.PRNGKey(0)
    ids = jnp.zeros((2, cfg.seq_len), jnp.int32)
    params = jax.eval_shape(model.init, rng, ids)
    batch = {"input_ids": ids, "labels": ids}

    def loss_fn(p):
        return gpt_lm_loss(model.apply, p, batch)

    closed_jaxpr, _ = _make_jaxpr_with_tree(loss_fn, params)
    for K in (2, 4, 8):
        eps = 0.6
        sliced = cluster_eqns_by_cost(closed_jaxpr, K, eps)
        assert len(sliced) == K
        fl = [sum(jaxpr_eqn_flops(e) for e in group) for group in sliced]
        total = sum(fl)
        assert max(fl) <= (1 + eps) * total / K * (1 + 1e-6), (K, fl)


def test_dp_reproduces_reference_balanced_solution_under_linear_scaling():
    """The reference's published 6.7B/16-GPU solution (2 balanced stages
    on (1,8) submeshes, ref suite_auto_gpt.py:71-74) came from MEASURED
    V100 costs with near-linear intra-op scaling on NVLink.  Feed the DP
    a cost tensor with that property (95% scaling efficiency at every
    width) and it must land on exactly that solution: equal max-stage
    cost across widths makes the sum term the tie-break, and the sum is
    minimized by the widest (fewest-stage) balanced partition."""
    L = 8
    sizes = [1, 2, 4, 8]
    eff = {1: 1.0, 2: 0.95, 4: 0.95, 8: 0.95}
    C = np.zeros((L, L, len(sizes)))
    per_layer = 1.0
    for m, n in enumerate(sizes):
        for i in range(L):
            for j in range(i, L):
                C[i, j, m] = per_layer * (j - i + 1) / (n * eff[n])
    part = stage_dp_solve(C, sizes, 16, 64)
    assert part == [(0, 4, 3), (4, 8, 3)], part


@pytest.mark.slow
def test_v100_like_calibration_search_is_cost_balanced():
    """Full search under a V100/NVLink-like analytic calibration (6.7B,
    16 devices, 64 microbatches, 8 auto layers).  The analytic MXU
    efficiency ladder penalizes narrow shards (~72% scaling at width 8),
    so with B=64 the DP rationally prefers deeper, narrower stages than
    the reference's measured-V100 2x(1,8) — see
    test_dp_reproduces_reference_balanced_solution_under_linear_scaling
    for the measured-like case.  What must ALWAYS hold: no degenerate
    mega-stage (the round-4 [7,1] artifact bug) and stages near
    cost-balance."""
    from alpa_tpu.mesh_profiling import (COLLECTIVE_KINDS,
                                         CalibratedCostModel,
                                         set_global_calibration)
    from benchmark.auto_search_artifact import search_gpt_plan

    # V100 DGX-ish: 125 TFLOPS fp16 peak with the usual efficiency
    # ladder, NVLink ~150 GB/s per-GPU collective bandwidth
    peak = 125e12
    eff = ((1e8, 0.15), (1e10, 0.40), (1e12, 0.55), (1e14, 0.60))
    dot_points = [(f, 1.0 / (e * peak)) for f, e in eff]
    ab = {kind: (1e-6, 1.0 / 150e9) for kind in COLLECTIVE_KINDS}
    set_global_calibration(CalibratedCostModel(dot_points, ab))
    try:
        # batch 512 (not the ref's 1024: that collides with seq_len in
        # the artifact script's dim0-based batch-invar detection)
        plan = search_gpt_plan("6.7B", n_devices=16, num_hosts=2,
                               batch_size=512, num_micro_batches=64)
    finally:
        set_global_calibration(None)
    ids = plan["forward_stage_layer_ids"]
    assert len(ids) >= 2
    counts = [len(s) for s in ids]
    # flops-balanced layering + a sane DP cannot produce a mega-stage
    assert max(counts) <= 3, ids
    assert sum(h * d for h, d in plan["submesh_shapes"]) == 16
