"""Certified plan superoptimization (ISSUE 17).

Oracle 1 (satellite 1): the DAG re-simulator's per-mesh simulated
peak-live-bytes pins bit-for-bit against the static liveness analysis'
``alpa_plan_peak_bytes`` on the committed 2-mesh fixture when both walk
the same serial order.  Oracle 2 (satellite 2): ``reshard_group_extent``
is the one grouping-legality oracle — its documented semantics (FREE
hopping, blocked slots, groupable-only multi-member, the
``superopt_max_group`` fission cap) hold on synthetic records, and the
registers-mode coalescer consumes it (fingerprint determinism over real
programs is covered by the compile-cache tests).  Oracle 3 (satellite
3): every adversarial fuzz class — reorder across a RAW edge, sink a
FREE past a live consumer, fuse a quantized edge into a batched group,
drop a microbatch accumulation RUN — is rejected by the verdict gate
with its named finding.  Oracle 4: on a real 2-mesh pipeline,
``superopt_mode=auto`` recovers a hazard-legal deoptimized plan with a
strict simulated critical-path AND peak-bytes improvement, training-step
outputs bitwise identical across baseline / deoptimized / rewritten
plans, and a warm restart replays the accepted rewrite from the compile
cache with zero search and an identical plan fingerprint.
"""
import dataclasses
import json
import os

import numpy as np
import pytest

import alpa_tpu
from alpa_tpu.analysis import plan_verifier as pv
from alpa_tpu.analysis import superopt as so
from alpa_tpu.analysis.critical_path import MemSpec, simulate_dag, whatif
from alpa_tpu.analysis.model_check import model_from_dict
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.runtime_emitter import (
    OpHook, PipelineInstType, PipelineInstruction, instruction_accesses)
from alpa_tpu.testing import create_mlp_train_state_and_batch

from tests.pipeline_parallel.test_plan_verifier import _compile_pipeline

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))),
    "benchmark", "results", "model_check_fixture_plan.json")


@pytest.fixture(autouse=True)
def _restore_globals():
    prev = {k: getattr(global_config, k) for k in (
        "pipeline_dispatch_mode", "verify_plans", "compile_cache_dir",
        "superopt_mode", "superopt_beam_width", "superopt_step_budget",
        "superopt_verify_budget", "superopt_max_group")}
    yield
    for k, v in prev.items():
        setattr(global_config, k, v)
    from alpa_tpu.compile_cache import reset_compile_cache
    reset_compile_cache()


# ---------------------------------------------------------------------
# satellite 1: simulated peaks pin against static liveness
# ---------------------------------------------------------------------

def _fixture_model():
    with open(FIXTURE, encoding="utf-8") as f:
        model, _hooks, _window = model_from_dict(json.load(f))
    return model


def _mem_from_model(model) -> MemSpec:
    writes = [list(op.writes) for op in model.ops]
    kills = [list(op.kills) for op in model.ops]
    slots = (model.slots.values() if isinstance(model.slots, dict)
             else model.slots)
    nbytes = {s.slot: float(s.nbytes) for s in slots}
    mesh_of = {s.slot: s.mesh for s in slots}
    written, preplaced = set(), set()
    for op in model.ops:
        for s in list(op.reads) + list(op.kills):
            if s not in written:
                preplaced.add(s)
        written.update(op.writes)
    return MemSpec(writes=writes, kills=kills, nbytes=nbytes,
                   mesh_of=mesh_of, num_meshes=model.num_meshes,
                   preplaced=frozenset(preplaced))


def test_simulated_peaks_match_static_liveness():
    """simulate_dag over the committed fixture, serialized in program
    order, reproduces check_liveness' per-mesh static peak bytes
    bit-for-bit — the two peak-live-bytes computations agree."""
    model = _fixture_model()
    n = len(model.ops)
    mem = _mem_from_model(model)
    durs = [1.0] * n
    preds = [set() if i == 0 else {i - 1} for i in range(n)]
    makespan, finish, peaks = simulate_dag(durs, preds, mem)
    assert makespan == float(n)
    assert len(finish) == n

    findings, stats = pv.check_liveness(model)
    assert not [f for f in findings if f.severity == "error"]
    static = stats["peak_bytes"]
    static_list = [static[str(m)] for m in range(model.num_meshes)] \
        if isinstance(static, dict) else list(static)
    assert list(peaks) == static_list, \
        f"simulated {peaks} != static {static_list}"
    assert static_list == [128.0, 192.0]    # pin the committed fixture


def test_whatif_returns_peaks_with_mem():
    mem = MemSpec(writes=[[0], [1], []], kills=[[], [0], [1]],
                  nbytes={0: 10.0, 1: 4.0}, mesh_of={0: 0, 1: 0},
                  num_meshes=1, preplaced=frozenset())
    durs = [2.0, 3.0, 1.0]
    preds = [set(), {0}, {1}]
    makespan, finish, peaks = simulate_dag(durs, preds, mem)
    assert makespan == 6.0
    # op 1 kills slot 0 before writing slot 1 (the liveness analysis'
    # within-op order), so the two never overlap
    assert peaks == [10.0]
    out = whatif(durs, preds, {1}, mem=mem)
    assert isinstance(out, tuple)
    ms2, peaks2 = out
    assert ms2 == 3.0
    assert peaks2 == [10.0]
    # without mem, whatif keeps its scalar contract
    assert whatif(durs, preds, {1}) == 3.0


# ---------------------------------------------------------------------
# layouts: serializable rewrite decisions
# ---------------------------------------------------------------------

def _toy_instructions():
    run = PipelineInstruction(PipelineInstType.RUN, info="r0")
    free = PipelineInstruction(
        PipelineInstType.FREE,
        free_keys=[("a", 0, 0), ("b", 0, 0)], info="f")
    run2 = PipelineInstruction(PipelineInstType.RUN, info="r1")
    return [run, free, run2]


def test_layout_check_apply_and_free_split():
    insts = _toy_instructions()
    ident = so.identity_layout(3)
    so.check_layout(insts, ident)
    assert so.apply_layout(insts, ident) == insts

    # free split: each key position emitted once, as its own FREE
    split = [0, ["free", 1, [0]], 2, ["free", 1, [1]]]
    so.check_layout(insts, split)
    out = so.apply_layout(insts, split)
    assert [o.opcode for o in out] == [
        PipelineInstType.RUN, PipelineInstType.FREE,
        PipelineInstType.RUN, PipelineInstType.FREE]
    assert out[1].free_keys == [("a", 0, 0)]
    assert out[3].free_keys == [("b", 0, 0)]

    # clone duplicates a RUN without consuming the original
    clone = [0, 1, 2, ["clone", 0]]
    so.check_layout(insts, clone)
    assert so.apply_layout(insts, clone)[3].info == "r0"

    with pytest.raises(ValueError, match="drops"):
        so.check_layout(insts, [0, 1])              # RUN 2 missing
    with pytest.raises(ValueError, match="twice"):
        so.check_layout(insts, [0, 0, 1, 2])        # RUN emitted twice
    with pytest.raises(ValueError, match="twice"):
        so.check_layout(insts, [0, 1, ["free", 1, [0]], 2])
    with pytest.raises(ValueError, match="non-RUN"):
        so.check_layout(insts, [0, 1, 2, ["clone", 1]])
    with pytest.raises(ValueError, match="out of range"):
        so.check_layout(insts, [0, ["free", 1, [5]], 2])


# ---------------------------------------------------------------------
# satellite 2: the shared grouping-legality oracle
# ---------------------------------------------------------------------

def _rec(kind, edge=None, ss=0, ds=1, groupable=True, slots=()):
    if kind == "RESHARD":
        return {"kind": kind, "edge": edge, "ss": ss, "ds": ds,
                "groupable": groupable}
    if kind == "FREE":
        return {"kind": kind, "slots": tuple(slots)}
    return {"kind": kind}


def test_reshard_group_extent_semantics():
    e, f = (0, 1), (1, 0)
    # adjacent same-edge groupables group; a FREE between them is
    # hopped and counted (it enabled the later member)
    recs = [_rec("RESHARD", e, 0, 1), _rec("FREE", slots=(9,)),
            _rec("RESHARD", e, 2, 3), _rec("RESHARD", f, 4, 5)]
    members, hopped, hops, nxt = so.reshard_group_extent(recs, 0)
    assert members == [0, 2] and hopped == [1] and hops == 1
    assert nxt == 3                 # different edge ends the group

    # a FREE of a later member's own slot blocks it from joining
    recs = [_rec("RESHARD", e, 0, 1), _rec("FREE", slots=(2,)),
            _rec("RESHARD", e, 2, 3)]
    members, hopped, hops, nxt = so.reshard_group_extent(recs, 0)
    assert members == [0] and hops == 0

    # non-groupable (quantized/collective) transfers never join a
    # multi-member group — in either position
    recs = [_rec("RESHARD", e, 0, 1),
            _rec("RESHARD", e, 2, 3, groupable=False)]
    assert so.reshard_group_extent(recs, 0)[0] == [0]
    recs = [_rec("RESHARD", e, 0, 1, groupable=False),
            _rec("RESHARD", e, 2, 3)]
    assert so.reshard_group_extent(recs, 0)[0] == [0]

    # a RUN ends the group; trailing FREEs are not charged as hops
    recs = [_rec("RESHARD", e, 0, 1), _rec("RESHARD", e, 2, 3),
            _rec("FREE", slots=(9,)), _rec("RUN"),
            _rec("RESHARD", e, 4, 5)]
    members, hopped, hops, nxt = so.reshard_group_extent(recs, 0)
    assert members == [0, 1] and hopped == [2] and hops == 0
    assert nxt == 3


def test_reshard_group_extent_fission_cap():
    e = (0, 1)
    recs = [_rec("RESHARD", e, 2 * i, 2 * i + 1) for i in range(3)]
    # uncapped: one 3-member group
    assert so.reshard_group_extent(recs, 0)[0] == [0, 1, 2]
    # superopt_max_group=2: the group splits and the caller resumes at
    # the first excluded member
    members, _, _, nxt = so.reshard_group_extent(recs, 0, max_members=2)
    assert members == [0, 1] and nxt == 2
    assert so.reshard_group_extent(recs, 2, max_members=2)[0] == [2]


def test_coalescer_honors_fission_knob():
    """The registers-mode coalescer consumes the shared oracle: the
    superopt_max_group knob caps real batched groups at lowering time
    without changing instruction semantics."""
    ex, *_ = _compile_pipeline(num_stages=2)
    base = ex._register_programs["registers"]
    lower = ex._make_lowerer("registers")
    global_config.superopt_max_group = 1
    capped = lower(ex.instructions)
    assert max((len(h.members) for h in capped.hooks
                if getattr(h, "members", None)), default=1) <= 1
    # group membership is a replay batching decision, not a semantic
    # one: the capped program touches the same slots
    assert capped.num_slots == base.num_slots
    assert capped.verdict is not None and not capped.verdict.errors


# ---------------------------------------------------------------------
# deoptimize / score / search (pure, over a real compiled plan)
# ---------------------------------------------------------------------

def test_deoptimize_is_legal_and_search_recovers():
    ex, *_ = _compile_pipeline(num_stages=2)
    insts = list(ex.instructions)
    cm = so._CostModel()
    nm = ex.num_meshes
    base = so.score_instructions(insts, nm, cm)

    pess = so.deoptimize_instructions(insts, cm)
    assert [id(x) for x in pess] != [id(x) for x in insts]
    worse = so.score_instructions(pess, nm, cm)
    assert worse.makespan_us > base.makespan_us + 1e-9
    assert worse.total_peak > base.total_peak + 1e-9

    # the pessimized order is hazard-legal: re-lowering it introduces
    # no new finding vs the baseline verdict
    lower = ex._make_lowerer("registers")
    baseline_prog = ex._register_programs["registers"]
    pess_prog = lower(pess)
    assert so.verdict_new_findings(
        baseline_prog.verdict, pess_prog.verdict) == []

    # search from the pessimized list strictly recovers BOTH objectives
    _, b2, best, log, cands = so.superopt_search(pess, nm, cm)
    assert cands, "no admissible strict improvement found"
    assert best.makespan_us < b2.makespan_us - 1e-9
    assert best.total_peak < b2.total_peak - 1e-9
    assert {e["family"] for e in log} >= {"reschedule", "free_motion"}


# ---------------------------------------------------------------------
# satellite 3: adversarial fuzz — every unsound rewrite class is
# rejected by the verdict gate with its named finding
# ---------------------------------------------------------------------

def _gate_names(ex, mutate):
    """Lower a mutated instruction list and return the gate's new
    (analysis, code) findings vs the compiled baseline."""
    baseline = ex._register_programs["registers"]
    insts = list(ex.instructions)
    mutated = mutate(insts)
    lower = ex._make_lowerer("registers")
    prog = lower(mutated)
    return so.verdict_new_findings(baseline.verdict, prog.verdict)


def test_fuzz_reorder_across_raw_edge_rejected():
    ex, *_ = _compile_pipeline(num_stages=2)

    def mutate(insts):
        j = next(i for i, x in enumerate(insts)
                 if x.opcode == PipelineInstType.RESHARD and
                 x.src_mesh != x.dst_mesh)
        return [insts[j]] + insts[:j] + insts[j + 1:]

    new = _gate_names(ex, mutate)
    assert ("deadlock", "deadlock.recv-before-send") in new, new


def test_fuzz_free_before_consumer_rejected():
    ex, *_ = _compile_pipeline(num_stages=2)

    def mutate(insts):
        # sink a FREE in front of the earliest reader of its keys
        for fi, x in enumerate(insts):
            if x.opcode != PipelineInstType.FREE:
                continue
            keys = {tuple(k) for k in x.free_keys}
            readers = [i for i in range(fi) if any(
                kind == "read" and tuple(k) in keys
                for k, kind in instruction_accesses(insts[i]))]
            writers = [i for i in range(fi) if any(
                kind == "write" and tuple(k) in keys
                for k, kind in instruction_accesses(insts[i]))]
            if readers and writers and min(writers) < min(readers):
                e = min(readers)
                out = insts[:fi] + insts[fi + 1:]
                out.insert(e, x)
                return out
        pytest.skip("no FREE with an earlier reader found")

    new = _gate_names(ex, mutate)
    assert ("liveness", "liveness.use-after-free") in new, new


def test_fuzz_drop_microbatch_accumulation_rejected():
    ex, *_ = _compile_pipeline(num_stages=2)

    def mutate(insts):
        # drop a grad-accumulation RUN (kills and rewrites the same key)
        for i, x in enumerate(insts):
            if x.opcode != PipelineInstType.RUN:
                continue
            acc = instruction_accesses(x)
            kills = {tuple(k) for k, kind in acc if kind == "kill"}
            writes = {tuple(k) for k, kind in acc if kind == "write"}
            if kills & writes:
                return insts[:i] + insts[i + 1:]
        pytest.skip("no accumulation RUN found")

    new = _gate_names(ex, mutate)
    assert any(a == "liveness" for a, _ in new), new
    assert ("liveness", "liveness.use-undefined") in new or \
        ("liveness", "liveness.free-undefined") in new, new


def test_fuzz_quantized_edge_fused_into_group_rejected():
    """Class (c) at the PlanModel level: batching a quantized transfer
    into a direct_p2p group is rejected by structure analysis."""
    model = _fixture_model()
    # the fixture's two same-edge RESHARDs, groupable direct_p2p
    ops = list(model.ops)
    ri = [i for i, o in enumerate(ops) if o.kind == "RESHARD"]
    assert len(ri) == 2
    for i in ri:
        ops[i] = dataclasses.replace(ops[i], strategy="direct_p2p",
                                     groupable=True)

    def hook(members):
        mem = [ops[m] for m in members]
        return OpHook(
            kind="launch", name="group", node=members[0],
            mesh=mem[0].mesh,
            reads=tuple(s for o in mem for s in o.reads),
            writes=tuple(s for o in mem for s in o.writes),
            kills=tuple(s for o in mem for s in o.kills),
            members=tuple(members))

    base_model = dataclasses.replace(model, ops=ops)
    base = pv.verify_model(base_model, hooks=[hook(ri)])
    assert "structure.group-nongroupable" not in \
        {f.code for f in base.findings()}

    # fuzz: fuse a quantized edge into the batched group
    bad_ops = list(ops)
    bad_ops[ri[1]] = dataclasses.replace(
        bad_ops[ri[1]], strategy="quantized", groupable=False)
    cand = pv.verify_model(dataclasses.replace(model, ops=bad_ops),
                           hooks=[hook(ri)])
    new = so.verdict_new_findings(base, cand)
    assert ("structure", "structure.group-nongroupable") in new, new


# ---------------------------------------------------------------------
# oracle 4: end-to-end auto recovery + bitwise outputs + warm replay
# ---------------------------------------------------------------------

def _fresh_pair():
    return create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)


def _param_leaves(state):
    import jax
    return [np.asarray(x) for x in jax.tree_util.tree_leaves(
        state.params)]


def _reset_lowering(ex):
    """Forget every lowered program + slot table so the next launch
    re-lowers ex.instructions from scratch (the replan hot-swap path,
    plus the slot tables — the instruction ORDER changed, so slot
    numbering changes too)."""
    ex._register_programs.clear()
    ex._register_program = None
    ex._reg_input_loads = None
    ex._reg_const_loads = None
    ex._reg_acc_slots = None
    ex._reg_output_specs = None
    ex._superopt_outcome = None
    ex._superopt_instructions = None


def test_auto_recovers_deoptimized_plan_bitwise(tmp_path):
    from alpa_tpu.compile_cache import reset_compile_cache
    from alpa_tpu.telemetry.metrics import get_registry
    ex, state, batch, step = _compile_pipeline(num_stages=2)
    global_config.compile_cache_dir = str(tmp_path)
    reset_compile_cache()

    s0, b0 = _fresh_pair()
    ns0, _ = step(s0, b0)
    want = _param_leaves(ns0)
    assert any(bool(np.any(x)) for x in want)

    # adversarial baseline: hazard-legal deoptimized stream, hot-swapped
    ex.instructions = so.deoptimize_instructions(list(ex.instructions))
    _reset_lowering(ex)
    ex._ensure_lowered("registers")
    s1, b1 = _fresh_pair()
    ns1, _ = step(s1, b1)
    assert all((a == b).all()
               for a, b in zip(want, _param_leaves(ns1))), \
        "deoptimized plan must stay semantically identical"

    # auto: search + verdict gate recover both objectives
    global_config.superopt_mode = "auto"
    _reset_lowering(ex)
    ex._ensure_lowered("registers")
    out = ex._superopt_outcome
    assert out is not None and out.accepted and out.searched
    assert not out.cache_hit
    assert out.critical_path_delta_us < 0
    assert out.peak_bytes_delta < 0
    assert out.fingerprint != out.baseline_fingerprint
    s2, b2 = _fresh_pair()
    ns2, _ = step(s2, b2)
    assert all((a == b).all()
               for a, b in zip(want, _param_leaves(ns2))), \
        "rewritten plan must be bitwise identical to the baseline"

    # the decision is observable: metrics, superopt.txt, the cache
    snap = get_registry().snapshot()
    assert snap.get("alpa_superopt_rewrites_accepted_total", 0) >= 1
    assert snap.get("alpa_superopt_critical_path_delta_us", 0) < 0
    assert snap.get("alpa_superopt_peak_bytes_delta", 0) < 0
    text = ex.get_superopt_text()
    assert "accepted" in text and out.fingerprint[:8] in text
    decisions = so.load_cached_decisions()
    assert decisions and \
        decisions[0]["decision"]["fingerprint"] == out.fingerprint

    # suggest: same decision replayed from cache, but NOT applied
    global_config.superopt_mode = "suggest"
    _reset_lowering(ex)
    prog = ex._ensure_lowered("registers")
    out2 = ex._superopt_outcome
    assert out2.cache_hit and not out2.searched and out2.accepted
    assert ex._superopt_instructions is None
    assert prog.fingerprint() == out2.baseline_fingerprint

    # warm restart: fresh memory tier, disk cache replays with zero
    # search and the identical plan fingerprint
    reset_compile_cache()
    global_config.superopt_mode = "auto"
    _reset_lowering(ex)
    ex._ensure_lowered("registers")
    out3 = ex._superopt_outcome
    assert out3.cache_hit and not out3.searched and out3.accepted
    assert out3.fingerprint == out.fingerprint
    s3, b3 = _fresh_pair()
    ns3, _ = step(s3, b3)
    assert all((a == b).all()
               for a, b in zip(want, _param_leaves(ns3)))

    # superopt.txt lands in the debug dump (dumping also ingests the
    # trace into the calibration store, so it comes after the
    # cache-replay legs — measured costs re-key the decision)
    from alpa_tpu import monitoring
    monitoring.dump_debug_info(ex, str(tmp_path / "dump"))
    assert (tmp_path / "dump" / "superopt.txt").exists()
