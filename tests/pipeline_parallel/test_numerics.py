"""Numerics certification (ISSUE 14 tentpole).

Oracle 1: the precision-flow abstract interpretation certifies a
hand-built quantized 2-mesh plan with the exact composed error bound
(``1/254`` of block max per int8 hop) and nothing but the per-hop
notes; a full-precision plan certifies with zero findings.  Oracle 2:
every mutation class is caught with its named finding — a quantized
weight edge (numerics.lossy-weight-path), quantized optimizer state
reached through a donated RUN (numerics.lossy-opt-state-path), a
composed bound over the budget (numerics.budget-exceeded), a
below-fp32 accumulator (numerics.bf16-accumulation warning) — and the
severities route through ``verify_model``'s merged verdict.  Oracle 3:
the committed fixture certifies deterministically, the perf gate pins
its exact bound/finding counts, and ``verify_tool.py numerics`` emits
the stable ``alpa-numerics/v1`` schema.  Oracle 4: on a real 2-mesh
pipeline the default knobs (quantization off) yield zero ``numerics.*``
findings, ``verify_plans_numerics="error"`` blocks the launch of an
over-budget quantized plan independently of ``verify_plans``, warm
restarts replay the identical verdict and re-export the gauges, and
``numerics.txt`` lands in the debug dump.
"""
import dataclasses
import json
import os
import subprocess
import sys

import pytest

import alpa_tpu
from alpa_tpu import PipeshardParallel
from alpa_tpu.analysis import model_check as mc
from alpa_tpu.analysis import numerics as num
from alpa_tpu.analysis import plan_verifier as pv
from alpa_tpu.global_env import global_config
from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
from alpa_tpu.pipeline_parallel.stage_construction import UniformStageOption
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
FIXTURE = os.path.join(REPO, "benchmark", "results",
                       "numerics_fixture_plan.json")

INT8_HOP = 1.0 / 254.0   # == reshard_codec.ERROR_BOUND["int8"]


@pytest.fixture(autouse=True)
def _restore_globals():
    prev = (global_config.pipeline_dispatch_mode,
            global_config.verify_plans,
            global_config.verify_plans_numerics,
            global_config.numerics_error_budget,
            global_config.reshard_quantize,
            global_config.reshard_quantize_min_bytes,
            global_config.compile_cache_dir)
    yield
    (global_config.pipeline_dispatch_mode,
     global_config.verify_plans,
     global_config.verify_plans_numerics,
     global_config.numerics_error_budget,
     global_config.reshard_quantize,
     global_config.reshard_quantize_min_bytes,
     global_config.compile_cache_dir) = prev
    from alpa_tpu.compile_cache import reset_compile_cache
    reset_compile_cache()


def _compile_pipeline(num_stages=2, mode="registers"):
    alpa_tpu.init("local")
    global_config.pipeline_dispatch_mode = mode
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=4),
        stage_option=UniformStageOption(num_stages=num_stages))
    step = get_mlp_train_step(method, use_value_and_grad=False)
    state, batch = create_mlp_train_state_and_batch(
        batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
        num_layers=4, manual_pipeline_layer=False)
    state, _ = step(state, batch)
    return step.get_last_executable(), state, batch, step


# ---------------------------------------------------------------------
# oracle 1 + 2: hand-built 2-mesh models
# ---------------------------------------------------------------------

_F32 = "float32"
_AVAL = ((4, 4), _F32)
_PREC = {"n_matmul": 1, "n_reduce": 0, "n_cast": 0,
         "min_accum": "float32", "below_fp32_accum": False}


def _slots():
    return {
        0: pv.SlotModel(0, "x@m0", 0, 0, (4, 4), _F32, 64,
                        preplaced=True, provenance="activation"),
        1: pv.SlotModel(1, "w@m0", -1, 0, (4, 4), _F32, 64,
                        preplaced=True, provenance="param"),
        2: pv.SlotModel(2, "h0@m0", 0, 0, (4, 4), _F32, 64),
        3: pv.SlotModel(3, "h0@m1", 0, 1, (4, 4), _F32, 64),
        4: pv.SlotModel(4, "out@m1", 0, 1, (4, 4), _F32, 64,
                        protected=True),
    }


def _ops():
    return [
        pv.OpModel(0, "RUN", 0, reads=(0, 1), writes=(2,),
                   in_avals=(_AVAL, _AVAL), out_avals=(_AVAL,),
                   precision=dict(_PREC), label="RUN stage0"),
        pv.OpModel(1, "RESHARD", 0, reads=(2,), writes=(3,),
                   edge=(0, 1), cross=True, nbytes=64,
                   strategy="quantized", codec="int8", groupable=False,
                   label="RESHARD h0 0->1 [int8]"),
        pv.OpModel(2, "RUN", 1, reads=(3,), writes=(4,),
                   in_avals=(_AVAL,), out_avals=(_AVAL,),
                   precision=dict(_PREC), label="RUN stage1"),
        pv.OpModel(3, "FREE", 0, kills=(2,), label="FREE h0@m0"),
        pv.OpModel(4, "FREE", 1, kills=(3,), label="FREE h0@m1"),
    ]


def _model(ops, slots=None, streams=None, deps=None):
    return pv.PlanModel(
        ops=ops, slots=slots or _slots(), num_meshes=2,
        streams=streams or [[0, 1, 3], [2, 4]],
        deps=deps if deps is not None else {2: {1}})


def _codes(res):
    return [f.code for f in res.findings]


def test_clean_quantized_model_certifies_with_exact_bound():
    res = num.check_numerics(_model(_ops()))
    assert res.ok, res.format()
    # one lossy hop -> one per-hop note, nothing else
    assert _codes(res) == ["numerics.quantized-reduction"]
    st = res.stats
    assert st["max_error_bound"] == INT8_HOP    # exact, not approx
    assert st["lossy_edges"] == {"int8": 1}
    assert st["n_lossy_collectives"] == 1
    assert st["n_bf16_reductions"] == 0
    [row] = st["bound_table"]                   # protected outputs only
    assert row["var"] == "out@m1"
    assert row["provenance"] == "activation"
    assert row["storage"] == "float32" and row["accum"] == "float32"
    assert row["bound"] == INT8_HOP
    assert list(row["hops"]) == ["0->1:int8"]


def test_full_precision_model_has_zero_findings():
    ops = _ops()
    ops[1] = dataclasses.replace(ops[1], strategy=None, codec=None,
                                 groupable=True)
    res = num.check_numerics(_model(ops))
    assert res.ok and not res.findings, res.format()
    assert res.stats["max_error_bound"] == 0.0
    assert res.stats["lossy_edges"] == {}
    [row] = res.stats["bound_table"]
    assert row["bound"] == 0.0 and not row["hops"]


def test_mutation_quantized_weight_edge_is_lossy_weight_path():
    ops = _ops()
    ops[1] = dataclasses.replace(ops[1], weight=True)
    res = num.check_numerics(_model(ops))
    assert not res.ok
    assert "numerics.lossy-weight-path" in _codes(res), res.format()
    [f] = [f for f in res.findings
           if f.code == "numerics.lossy-weight-path"]
    assert "int8" in f.message and f.op == 1


def test_mutation_donated_opt_state_is_lossy_opt_state_path():
    """Provenance flows through a RUN only via *donated* inputs: an
    in-place optimizer update keeps opt_state provenance, so quantizing
    its output is the named error."""
    slots = _slots()
    slots[0] = dataclasses.replace(slots[0], provenance="opt_state")
    ops = _ops()
    ops[0] = dataclasses.replace(ops[0], kills=(0,))    # donation
    res = num.check_numerics(_model(ops, slots=slots))
    assert not res.ok
    assert "numerics.lossy-opt-state-path" in _codes(res), res.format()


def test_read_only_param_input_does_not_taint_activations():
    """The counterpart of the donation rule: stage0 *reads* the param
    slot (no donation), so its output is a fresh activation and the
    quantized hop is merely the per-hop note."""
    res = num.check_numerics(_model(_ops()))
    assert res.ok
    assert "numerics.lossy-weight-path" not in _codes(res)
    [row] = res.stats["bound_table"]
    assert row["provenance"] == "activation"


def test_mutation_fp8_hop_exceeds_default_budget():
    ops = _ops()
    ops[1] = dataclasses.replace(ops[1], codec="fp8")
    res = num.check_numerics(_model(ops))        # 0.07 > 0.05 default
    assert not res.ok
    assert "numerics.budget-exceeded" in _codes(res), res.format()
    assert res.stats["max_error_bound"] == 0.07
    # a loosened budget clears it (the knob keys the verdict cache)
    res2 = num.check_numerics(_model(ops), budget=0.1)
    assert res2.ok
    assert "numerics.budget-exceeded" not in _codes(res2)


def test_mutation_bf16_accumulation_is_warning():
    ops = _ops()
    ops[2] = dataclasses.replace(
        ops[2], precision={"n_matmul": 1, "n_reduce": 2, "n_cast": 0,
                           "min_accum": "bfloat16",
                           "below_fp32_accum": True})
    res = num.check_numerics(_model(ops))
    assert res.ok                       # warning-class, not error
    assert "numerics.bf16-accumulation" in _codes(res), res.format()
    assert res.stats["n_bf16_reductions"] == 1
    [row] = res.stats["bound_table"]
    assert row["accum"] == "bfloat16"


def test_verify_model_merges_numerics_severities():
    """The sixth analysis routes through the shared verdict: errors
    block, warnings warn, per-hop records land as notes, and the stats
    section is attached verbatim."""
    ops = _ops()
    ops[1] = dataclasses.replace(ops[1], weight=True)
    ops[2] = dataclasses.replace(
        ops[2], precision=dict(_PREC, min_accum="bfloat16",
                               below_fp32_accum=True))
    verdict = pv.verify_model(_model(ops), numerics=True)
    assert not verdict.ok
    assert "numerics.lossy-weight-path" in {f.code for f in
                                            verdict.errors}
    assert "numerics.bf16-accumulation" in {f.code for f in
                                            verdict.warnings}
    assert "numerics.quantized-reduction" in {f.code for f in
                                              verdict.notes}
    assert verdict.stats["numerics"]["lossy_edges"] == {"int8": 1}
    # ... and numerics=False leaves the verdict numerics-free
    clean = pv.verify_model(_model(_ops()), numerics=False)
    assert "numerics" not in clean.stats
    assert not [f for f in clean.findings()
                if f.code.startswith("numerics.")]


# ---------------------------------------------------------------------
# oracle 3: committed fixture, perf gate, tooling schema
# ---------------------------------------------------------------------

def test_fixture_certifies_and_perf_gate_pins_it():
    model, hooks, _ = mc.load_fixture(FIXTURE)
    res = num.check_numerics(model, hooks=hooks)
    assert res.ok, res.format()
    assert _codes(res) == ["numerics.quantized-reduction"] * 2
    assert res.stats["max_error_bound"] == 2 * INT8_HOP
    assert res.stats["lossy_edges"] == {"int8": 2}
    [row] = res.stats["bound_table"]
    assert row["var"] == "out" and list(row["hops"]) == \
        ["0->1:int8", "1->0:int8"]
    # the full six-analysis verdict is clean (the fixture is a real,
    # well-formed plan, not just a numerics prop)
    verdict = pv.verify_model(model, hooks=hooks, numerics=True)
    assert verdict.ok and not verdict.warnings, verdict.format_table()
    from benchmark.perf_gate import gate
    gv = gate({
        "numerics.findings_total": float(len(res.findings)),
        "numerics.lossy_edges":
            float(sum(res.stats["lossy_edges"].values())),
        "numerics.max_error_bound": float(res.stats["max_error_bound"]),
        "numerics.seconds": float(res.stats["seconds"]),
    })
    checked = {c["metric"] for c in gv["checks"]}
    assert {"numerics.findings_total", "numerics.lossy_edges",
            "numerics.max_error_bound", "numerics.seconds"} <= checked
    assert gv["pass"], gv


def test_export_metrics_sets_gauges_from_stats():
    model, hooks, _ = mc.load_fixture(FIXTURE)
    res = num.check_numerics(model, hooks=hooks)
    num._MAX_BOUND.set(0.0)
    num.export_metrics(res.stats)
    assert num._MAX_BOUND.value == 2 * INT8_HOP
    assert num._LOSSY_EDGES.labels("int8").value == 2.0
    # SET (not inc): a replay exports the identical values
    num.export_metrics(res.stats)
    assert num._LOSSY_EDGES.labels("int8").value == 2.0


def test_verify_tool_numerics_schema_and_exit_status():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join("scripts", "verify_tool.py"),
         "numerics", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, check=False)
    assert out.returncode == 0, out.stderr
    doc = json.loads(out.stdout)
    assert doc["schema"] == "alpa-numerics/v1"
    assert doc["ok"] is True
    assert doc["stats"]["max_error_bound"] == 2 * INT8_HOP
    assert {f["code"] for f in doc["findings"]} == \
        {"numerics.quantized-reduction"}
    assert all(f["severity"] == "note" for f in doc["findings"])
    # an unmeetable budget flips ok and the exit status
    out = subprocess.run(
        [sys.executable, os.path.join("scripts", "verify_tool.py"),
         "numerics", "--error-budget", "1e-4", "--json"],
        capture_output=True, text=True, cwd=REPO, env=env, check=False)
    assert out.returncode == 1, out.stderr
    doc = json.loads(out.stdout)
    assert doc["ok"] is False
    assert "numerics.budget-exceeded" in {f["code"]
                                          for f in doc["findings"]}


# ---------------------------------------------------------------------
# oracle 4: real 2-mesh pipeline end to end
# ---------------------------------------------------------------------

def test_default_knobs_produce_zero_numerics_findings():
    """Quantization is off by default: the certification runs (stats
    attach) but every bound is 0.0 and no numerics.* finding fires."""
    ex, *_ = _compile_pipeline(num_stages=2)
    verdict = ex._register_programs["registers"].verdict
    assert verdict is not None and verdict.ok
    st = verdict.stats["numerics"]
    assert st["max_error_bound"] == 0.0
    assert st["lossy_edges"] == {}
    assert st["n_tracked"] > 0
    assert not [f for f in verdict.findings()
                if f.code.startswith("numerics.")]


def test_numerics_off_skips_analysis_entirely():
    global_config.verify_plans_numerics = "off"
    ex, *_ = _compile_pipeline(num_stages=2)
    verdict = ex._register_programs["registers"].verdict
    assert verdict is not None and verdict.ok
    assert "numerics" not in verdict.stats


def test_quantized_pipeline_certifies_then_error_mode_blocks_launch():
    """With the codec on, real cross-stage activations pick up composed
    int8 bounds (certified under the default budget); tightening the
    budget under verify_plans_numerics='error' refuses the launch with
    PlanVerificationError — independently of verify_plans (left at
    'warn')."""
    global_config.reshard_quantize = "int8"
    global_config.reshard_quantize_min_bytes = 1
    ex, state, batch, step = _compile_pipeline(num_stages=2)
    verdict = ex._register_programs["registers"].verdict
    st = verdict.stats["numerics"]
    assert sum(st["lossy_edges"].values()) >= 1, st
    assert st["max_error_bound"] >= INT8_HOP
    assert verdict.ok, verdict.format_table()   # activations may lose
    # tighten below one int8 hop; the budget keys the verdict cache, so
    # re-lowering re-runs the analysis instead of replaying the pass
    global_config.numerics_error_budget = 1e-4
    global_config.verify_plans_numerics = "error"
    assert global_config.verify_plans == "warn"
    ex._register_programs = {}
    ex._register_program = None
    try:
        with pytest.raises(pv.PlanVerificationError) as exc_info:
            step(state, batch)
        assert "numerics.budget-exceeded" in str(exc_info.value)
    finally:
        ex._register_programs = {}
        ex._register_program = None


def test_warm_restart_replays_verdict_and_reexports_gauges(tmp_path):
    from alpa_tpu.compile_cache import (get_compile_cache,
                                        reset_compile_cache)
    global_config.compile_cache_dir = str(tmp_path)
    global_config.reshard_quantize = "int8"
    global_config.reshard_quantize_min_bytes = 1
    reset_compile_cache()
    ex, *_ = _compile_pipeline(num_stages=2)
    cold = ex._register_programs["registers"].verdict
    assert cold.stats["numerics"]["lossy_edges"], cold.stats
    # warm restart: wipe the lowering and the in-memory tier
    reset_compile_cache()
    ex._register_programs = {}
    ex._register_program = None
    num._MAX_BOUND.set(0.0)
    ex._ensure_lowered("registers")
    warm = ex._register_programs["registers"].verdict
    assert warm.to_dict() == cold.to_dict()
    # the cache-hit path re-exports the gauges from the replayed stats
    assert num._MAX_BOUND.value == \
        cold.stats["numerics"]["max_error_bound"]
    stats = get_compile_cache().stats()["namespaces"]["plan_verdict"]
    assert stats["hits"] >= 1, stats


def test_numerics_txt_in_debug_dump(tmp_path):
    from alpa_tpu.monitoring import dump_debug_info
    global_config.reshard_quantize = "int8"
    global_config.reshard_quantize_min_bytes = 1
    ex, *_ = _compile_pipeline(num_stages=2)
    dump_debug_info(ex, str(tmp_path))
    path = tmp_path / "numerics.txt"
    assert path.exists()
    text = path.read_text()
    assert "numerics certification" in text
    assert "int8=" in text and "per-output bounds:" in text
