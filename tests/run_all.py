"""Run every test file in its own subprocess.

Analog of ref ``tests/run_all.py`` (SURVEY.md §4): per-file process
isolation (fresh jax runtime per file), timeout per file, run/skip
patterns.

  python tests/run_all.py [--run-pattern PAT] [--skip-pattern PAT]
                          [--timeout SECONDS]
"""
import argparse
import glob
import os
import subprocess
import sys
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--run-pattern", default=None)
    parser.add_argument("--skip-pattern", default=None)
    parser.add_argument("--timeout", type=int, default=1000)
    args = parser.parse_args()

    test_dir = os.path.dirname(os.path.abspath(__file__))
    files = sorted(
        glob.glob(os.path.join(test_dir, "**", "test_*.py"),
                  recursive=True))
    if args.run_pattern:
        files = [f for f in files if args.run_pattern in f]
    if args.skip_pattern:
        files = [f for f in files if args.skip_pattern not in f]

    failed = []
    for f in files:
        rel = os.path.relpath(f, test_dir)
        tic = time.time()
        try:
            r = subprocess.run(
                [sys.executable, "-m", "pytest", f, "-x", "-q"],
                timeout=args.timeout,
                cwd=os.path.dirname(test_dir))
            ok = r.returncode == 0
        except subprocess.TimeoutExpired:
            ok = False
        status = "PASS" if ok else "FAIL"
        print(f"[{status}] {rel} ({time.time() - tic:.1f}s)", flush=True)
        if not ok:
            failed.append(rel)

    print(f"\n{len(files) - len(failed)}/{len(files)} files passed")
    if failed:
        print("failed:", failed)
        sys.exit(1)


if __name__ == "__main__":
    main()
