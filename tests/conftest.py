"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors SURVEY.md §4's implication (d)/(e): single-host multi-chip tests
stand in for a pod; compile-only tests need no TPU at all.
"""
import os

# Must run before the first backend use: force an 8-device virtual CPU
# mesh.  Set ALPA_TPU_TEST_ON_TPU=1 to keep the real backend (tests/tpu/).
_on_tpu = os.environ.get("ALPA_TPU_TEST_ON_TPU") == "1"
if not _on_tpu:
    from alpa_tpu.platform import pin_cpu_platform
    pin_cpu_platform(8)
os.environ["ALPA_TPU_TESTING"] = "1"

import pytest  # noqa: E402

import alpa_tpu  # noqa: E402


@pytest.fixture(autouse=True)
def reset_cluster_state():
    yield
    alpa_tpu.shutdown()


@pytest.fixture(autouse=True)
def isolated_compile_cache():
    """Each test gets a fresh, memory-only compile cache: no cross-test
    hit/miss bleed, and a developer's ALPA_TPU_CACHE_DIR never leaks
    persisted solver decisions into (or out of) the test run.  Tests that
    want a disk tier point ``global_config.compile_cache_dir`` at a
    tmp_path and call ``reset_compile_cache()`` themselves."""
    from alpa_tpu.compile_cache import reset_compile_cache
    from alpa_tpu.global_env import global_config
    prev_dir = global_config.compile_cache_dir
    global_config.compile_cache_dir = None
    reset_compile_cache()
    yield
    global_config.compile_cache_dir = prev_dir
    reset_compile_cache()
