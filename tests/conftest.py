"""Test configuration: run everything on a virtual 8-device CPU mesh.

Mirrors SURVEY.md §4's implication (d)/(e): single-host multi-chip tests
stand in for a pod; compile-only tests need no TPU at all.
"""
import os

# Must be set before the first backend use: force an 8-device virtual CPU
# mesh.  (The axon sitecustomize may have imported jax already and pinned
# jax_platforms, so we also override via jax.config below.)
# Set ALPA_TPU_TEST_ON_TPU=1 to keep the real backend (for tests/tpu/).
_on_tpu = os.environ.get("ALPA_TPU_TEST_ON_TPU") == "1"
_flags = os.environ.get("XLA_FLAGS", "")
if not _on_tpu and "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags +
                               " --xla_force_host_platform_device_count=8")
import jax  # noqa: E402

if not _on_tpu:
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
os.environ["ALPA_TPU_TESTING"] = "1"

import pytest  # noqa: E402

import alpa_tpu  # noqa: E402


@pytest.fixture(autouse=True)
def reset_cluster_state():
    yield
    alpa_tpu.shutdown()
