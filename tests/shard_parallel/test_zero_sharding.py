"""ZeRO weight-update sharding (ISSUE 10): path classification,
cost-modeled optimizer-state partitioning, and numeric equivalence.

Oracle: ZeRO-2/ZeRO-3 are pure *layout* changes — losses must match the
replicated data-parallel baseline bitwise; the memory-budgeted ILP must
pick sharded optimizer state on its own (chosen by cost, not forced).
"""
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu.parallel_method import (DataParallel, ShardParallel,
                                      Zero2Parallel, Zero3Parallel)
from alpa_tpu.shard_parallel.auto_sharding import (
    AutoShardingOption, is_opt_state_path, is_param_path, path_components,
    resolved_zero_stage)
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)


class TestPathClassification:
    """plan_rule_based used to match optimizer-state leaves by raw
    substring (``"nu" in path`` also hit ``num_*``); classification now
    matches path *components*."""

    def test_opt_state_paths(self):
        assert is_opt_state_path("[0].opt_state[0].mu['Dense_0']['kernel']")
        assert is_opt_state_path("[0].opt_state[0].nu['head']['bias']")
        assert is_opt_state_path(".opt_state.trace['Dense_0']['kernel']")
        assert is_opt_state_path(".mu['Dense_0']['kernel']")

    def test_adversarial_param_names_are_not_opt_state(self):
        # "nu" inside "num_embeddings"/"nu_head" and "trace" inside
        # "trace_proj" must NOT classify as optimizer state
        for path in (".params['num_embeddings']['kernel']",
                     ".params['nu_head']['kernel']",
                     ".params['trace_proj']['bias']",
                     ".params['momentum_encoder']['kernel']"):
            assert not is_opt_state_path(path), path
            assert is_param_path(path), path

    def test_mirror_tree_precedence(self):
        # optax moment trees mirror the params tree: a "params" component
        # under opt_state is still optimizer state
        p = "[0].opt_state[0].mu['params']['Dense_0']['kernel']"
        assert is_opt_state_path(p)
        assert not is_param_path(p)

    def test_path_components(self):
        assert path_components(".opt_state[0].mu['nu_head']") == \
            ("opt_state", "0", "mu", "nu_head")

    def test_resolved_zero_stage(self):
        assert resolved_zero_stage(AutoShardingOption(zero_stage="0")) == 0
        assert resolved_zero_stage(AutoShardingOption(zero_stage="2")) == 2
        assert resolved_zero_stage(AutoShardingOption(zero_stage="3")) == 3
        assert resolved_zero_stage(AutoShardingOption()) == -1
        # legacy flags force a stage under "auto"
        assert resolved_zero_stage(AutoShardingOption(
            prefer_reduce_scatter=True)) == 2
        assert resolved_zero_stage(AutoShardingOption(
            force_zero_stage_3=True)) == 3
        with pytest.raises(ValueError, match="zero_stage"):
            resolved_zero_stage(AutoShardingOption(zero_stage="1"))


def _train(method, n_steps=2, batch_size=16, hidden_dim=64):
    state, batch = create_mlp_train_state_and_batch(
        batch_size, hidden_dim=hidden_dim)
    step = get_mlp_train_step(method, use_value_and_grad=True)
    for _ in range(n_steps):
        state, loss = step(state, batch)
    return state, loss, step.get_last_executable()


def _sharded_input_count(ex):
    n = 0
    for sh, av in zip(ex.in_shardings, ex.in_avals):
        if av.shape and np.prod(sh.shard_shape(av.shape)) < \
                np.prod(av.shape):
            n += 1
    return n


class TestZeroNumerics:
    """ZeRO stages vs replicated DP: identical losses, sharded state."""

    def test_zero2_bit_exact_vs_dp(self):
        alpa_tpu.init("local")
        _, loss_dp, _ = _train(DataParallel())
        state2, loss_z2, ex2 = _train(Zero2Parallel())
        np.testing.assert_array_equal(np.asarray(loss_dp),
                                      np.asarray(loss_z2))
        # the optimizer-state leaves really are partitioned
        opt_leaf = state2.opt_state[0].trace["params"]["Dense_0"]["kernel"]
        assert np.prod(opt_leaf.sharding.shard_shape(opt_leaf.shape)) < \
            np.prod(opt_leaf.shape)

    def test_zero3_bit_exact_vs_dp(self):
        alpa_tpu.init("local")
        _, loss_dp, _ = _train(DataParallel())
        state3, loss_z3, _ = _train(Zero3Parallel())
        np.testing.assert_array_equal(np.asarray(loss_dp),
                                      np.asarray(loss_z3))
        # ZeRO-3 also shards the parameters
        p = state3.params["params"]["Dense_0"]["kernel"]
        assert np.prod(p.sharding.shard_shape(p.shape)) < np.prod(p.shape)

    def test_zero_stage_knob_forces_sharding(self):
        alpa_tpu.init("local")
        _, loss0, ex0 = _train(ShardParallel(
            auto_sharding_option=AutoShardingOption(zero_stage="0")))
        _, loss2, ex2 = _train(ShardParallel(
            auto_sharding_option=AutoShardingOption(zero_stage="2")))
        np.testing.assert_array_equal(np.asarray(loss0),
                                      np.asarray(loss2))
        assert _sharded_input_count(ex2) > _sharded_input_count(ex0)
        # zero_stage is part of the parallel plan: resume validation
        # (checkpoint manager) must distinguish the two layouts
        assert ex0.get_plan_fingerprint() != ex2.get_plan_fingerprint()


class TestCostModeledChoice:
    """The tentpole claim: ZeRO-2 chosen BY COST under ``zero_stage=
    "auto"`` — a per-device memory budget that replicated optimizer
    state cannot satisfy flips the ILP to reduce-scatter-aware sharded
    strategies; a generous budget keeps replication (all-gather latency
    is charged, memory is not needed)."""

    def _state_bytes(self):
        import jax
        state, _ = create_mlp_train_state_and_batch(16, hidden_dim=64)
        return sum(
            np.prod(a.shape) * a.dtype.itemsize
            for a in jax.tree_util.tree_leaves(state)
            if hasattr(a, "shape") and a.shape)

    def test_budget_flips_ilp_to_sharded_opt_state(self):
        alpa_tpu.init("local")
        _, loss_g, ex_g = _train(ShardParallel(
            auto_sharding_option=AutoShardingOption()))
        tight = int(self._state_bytes() * 0.66)
        _, loss_t, ex_t = _train(ShardParallel(
            auto_sharding_option=AutoShardingOption(
                memory_budget_per_device=tight)))
        # same math, different layout
        np.testing.assert_array_equal(np.asarray(loss_g),
                                      np.asarray(loss_t))
        assert _sharded_input_count(ex_t) > _sharded_input_count(ex_g)
