"""ILP / greedy-fallback unit tests (no device work).

The greedy fallback must enforce ``memory_budget_per_device`` as hard as
the MILP does (ref auto_sharding's memory constraint) — an OOM layout
must never be silently "chosen".
"""
import numpy as np
import pytest

from alpa_tpu.shard_parallel.ilp import (InfeasibleMemoryBudget,
                                         _solve_greedy, solve_strategy_graph)
from alpa_tpu.shard_parallel.strategy import (Edge, Node, Strategy,
                                              StrategyGraph)


def _invar_node(idx, mem_options):
    """An invar node with (replicated, sharded) strategies: the replicated
    one is comm-free but heavy; the sharded one costs comm but is light."""
    strategies = [
        Strategy(name=f"s{k}", out_spec=(), comm_cost=float(k),
                 mem_bytes=float(m))
        for k, m in enumerate(mem_options)
    ]
    return Node(idx=idx, kind="invar", aval=None, strategies=strategies,
                invar_idx=idx)


def _graph(nodes, edges=()):
    return StrategyGraph(list(nodes), list(edges), None)


class TestGreedyMemoryBudget:

    def test_budget_respected(self):
        # replicated = 100 B (cost 0), sharded = 10 B (cost 1) per node;
        # budget 50 forces sharded everywhere despite higher comm cost.
        g = _graph([_invar_node(i, [100, 10]) for i in range(4)])
        choice = _solve_greedy(g, [2] * 4, memory_budget=50)
        used = sum(g.nodes[i].strategies[choice[i]].mem_bytes
                   for i in range(4))
        assert used <= 50, (choice, used)
        assert choice == [1, 1, 1, 1]

    def test_partial_budget_picks_cheapest_mix(self):
        # budget lets exactly one node stay replicated
        g = _graph([_invar_node(i, [100, 10]) for i in range(4)])
        choice = _solve_greedy(g, [2] * 4, memory_budget=130)
        used = sum(g.nodes[i].strategies[choice[i]].mem_bytes
                   for i in range(4))
        assert used <= 130, (choice, used)
        assert sum(1 for c in choice if c == 0) == 1

    def test_infeasible_raises(self):
        g = _graph([_invar_node(i, [100, 10]) for i in range(4)])
        with pytest.raises(InfeasibleMemoryBudget):
            _solve_greedy(g, [2] * 4, memory_budget=30)

    def test_infeasible_propagates_through_driver(self):
        g = _graph([_invar_node(i, [100, 10]) for i in range(4)])
        with pytest.raises(InfeasibleMemoryBudget):
            solve_strategy_graph(g, time_limit=1, memory_budget=30)

    def test_refinement_cannot_break_budget(self):
        # An edge strongly prefers node 1 replicated; the budget forbids
        # both nodes replicated — refinement must not flip into OOM.
        n0 = _invar_node(0, [100, 10])
        n1 = _invar_node(1, [100, 10])
        cost = np.array([[0.0, 500.0], [500.0, 500.0]])
        g = _graph([n0, n1], [Edge(0, 1, cost)])
        choice = _solve_greedy(g, [2, 2], memory_budget=120)
        used = sum(g.nodes[i].strategies[choice[i]].mem_bytes
                   for i in (0, 1))
        assert used <= 120, (choice, used)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
