"""Certified quantized gradient collectives (ISSUE 19).

Oracles:

1. **Codec contract** — stochastic rounding is unbiased (the mean over
   seeds converges on the exact value), every single-shot error sits
   inside the documented ``ERROR_BOUND`` (pinned FROM the dict, so the
   dict stays the single source of truth), error feedback keeps the
   *cumulative* multi-step error inside the single-shot bound, and the
   quantize→partial-reduce→requantize reduce-scatter composition honors
   the two-hop ``grad_*_rs`` entries — including ragged, all-zero and
   deep-denormal blocks.
2. **Cost-modeled choice** — the ILP flips eligible gradient tensors to
   the quantized reduce-scatter per tensor (``grad_quantize_min_bytes``
   draws the line); the plan-time counters record each choice.
3. **Byte identity at `off`** — default knobs produce bitwise-identical
   losses and identical plan fingerprints/cache keys (the token only
   exists when the knob is on).
4. **Certification** — the pipeshard seven-analysis verdict composes a
   non-trivial end-to-end gradient bound under the budget; shrinking
   ``numerics_error_budget`` below it blocks the launch; warm restarts
   replay the identical fingerprint with zero ILP solves.
"""
import numpy as np
import pytest

import alpa_tpu
import jax
import jax.numpy as jnp

from alpa_tpu.global_env import global_config
from alpa_tpu.parallel_method import ShardParallel, Zero2Parallel
from alpa_tpu.pipeline_parallel import reshard_codec as codec
from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                              get_mlp_train_step)

GRAD_MODES = ("int8",) + (("fp8",) if codec.have_fp8() else ())


@pytest.fixture(autouse=True)
def _restore_globals():
    prev = (global_config.grad_quantize,
            global_config.grad_quantize_min_bytes,
            global_config.grad_error_feedback,
            global_config.verify_plans_numerics,
            global_config.numerics_error_budget,
            global_config.compile_cache_dir)
    yield
    (global_config.grad_quantize,
     global_config.grad_quantize_min_bytes,
     global_config.grad_error_feedback,
     global_config.verify_plans_numerics,
     global_config.numerics_error_budget,
     global_config.compile_cache_dir) = prev
    from alpa_tpu.compile_cache import reset_compile_cache
    reset_compile_cache()


def _blockmax(x):
    """Per-element bound scale: the 256-block max magnitude, expanded."""
    flat = np.ravel(np.asarray(x, np.float32))
    n = flat.size
    nb = -(-n // codec.BLOCK)
    padded = np.pad(flat, (0, nb * codec.BLOCK - n))
    bm = np.abs(padded.reshape(nb, codec.BLOCK)).max(axis=1)
    return np.repeat(bm, codec.BLOCK)[:n]


class TestGradCodecContract:
    """Property tests pinned FROM ``ERROR_BOUND`` — the dict is the
    contract; the assertions read it rather than re-deriving numbers."""

    @pytest.mark.parametrize("mode", GRAD_MODES)
    def test_stochastic_rounding_is_unbiased(self, mode):
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.standard_normal(512).astype(np.float32))
        bound = codec.ERROR_BOUND[f"grad_{mode}"]
        n_seeds = 256
        acc = np.zeros(512, np.float64)
        for s in range(n_seeds):
            g_hat, _ = codec.grad_compress(x, mode, jax.random.PRNGKey(s))
            err = np.asarray(g_hat, np.float64) - np.asarray(x, np.float64)
            # every single shot inside the documented bound
            assert np.all(np.abs(err) <= bound * _blockmax(x) + 1e-7), \
                np.abs(err).max()
            acc += np.asarray(g_hat, np.float64)
        mean_err = np.abs(acc / n_seeds - np.asarray(x, np.float64))
        # the dither mean converges on the exact value: standard error
        # of the mean is step/(2*sqrt(N)); 6 sigma keeps this robust
        tol = bound * _blockmax(x) * (6.0 / (2.0 * np.sqrt(n_seeds)))
        assert np.all(mean_err <= tol + 1e-7), \
            (mean_err / np.maximum(_blockmax(x), 1e-30)).max()

    @pytest.mark.parametrize("mode", GRAD_MODES)
    def test_error_feedback_amortizes_cumulative_error(self, mode):
        """Telescoping: the transmitted sum over k steps misses the true
        sum by exactly the final residual — one single-shot bound, not
        k of them.  Without the residual the worst case is additive."""
        rng = np.random.default_rng(3)
        g = jnp.asarray(rng.standard_normal(600).astype(np.float32) * 0.3)
        bound = codec.ERROR_BOUND[f"grad_{mode}"]
        k = 8
        res = None
        sent = np.zeros(600, np.float64)
        for step in range(k):
            g_hat, res = codec.grad_compress(
                g, mode, jax.random.PRNGKey(100 + step), residual=res)
            sent += np.asarray(g_hat, np.float64)
        cum_err = np.abs(sent - k * np.asarray(g, np.float64))
        # the residual input can push a block slightly over g's blockmax,
        # so allow one bound's worth of headroom on the scale itself
        single_shot = bound * _blockmax(g) * (1.0 + bound) + 1e-6
        assert np.all(cum_err <= single_shot), \
            (cum_err / np.maximum(_blockmax(g), 1e-30)).max()
        assert np.all(cum_err <= k * bound * _blockmax(g) + 1e-6)

    @pytest.mark.parametrize("mode", GRAD_MODES)
    def test_ragged_zero_and_denormal_blocks_within_bound(self, mode):
        bound = codec.ERROR_BOUND[f"grad_{mode}"]
        rng = np.random.default_rng(11)
        cases = [
            rng.standard_normal(1000).astype(np.float32),      # ragged
            np.zeros(300, np.float32),                         # zero
            (rng.standard_normal(512) * 1e-40).astype(np.float32),
        ]
        for arr in cases:
            x = jnp.asarray(arr)
            g_hat, res = codec.grad_compress(x, mode,
                                             jax.random.PRNGKey(5))
            err = np.abs(np.asarray(g_hat, np.float64) -
                         np.asarray(arr, np.float64))
            assert np.all(np.isfinite(np.asarray(g_hat))), arr[:4]
            # relative bound from the dict; blocks under the FTZ scale
            # floor degrade to one absolute floor step (see
            # reshard_codec._SCALE_FLOOR)
            assert np.all(err <= bound * _blockmax(arr) +
                          float(codec._SCALE_FLOOR)), \
                (err.max(), _blockmax(arr).max())
            if not arr.any():
                # all-zero blocks are bit-exact with zero residual
                assert not np.asarray(g_hat).any()
                assert not np.asarray(res).any()

    @pytest.mark.parametrize("mode", GRAD_MODES)
    def test_reduce_scatter_two_hop_bound(self, mode):
        rng = np.random.default_rng(23)
        grads = [jnp.asarray(rng.standard_normal(700).astype(np.float32))
                 for _ in range(4)]
        mean_hat, new_res = codec.grad_reduce_scatter(
            grads, mode, jax.random.PRNGKey(9))
        true_mean = np.mean([np.asarray(g, np.float64) for g in grads],
                            axis=0)
        bound_rs = codec.ERROR_BOUND[f"grad_{mode}_rs"]
        scale = np.max([_blockmax(g) for g in grads], axis=0)
        err = np.abs(np.asarray(mean_hat, np.float64) - true_mean)
        assert np.all(err <= bound_rs * scale * (1.0 + bound_rs) + 1e-6)
        assert len(new_res) == 4

    def test_grad_error_bound_composes_from_the_dict(self):
        eb = codec.ERROR_BOUND
        for mode in ("int8", "fp8"):
            assert codec.grad_error_bound(mode) == eb[f"grad_{mode}"]
            assert codec.grad_error_bound(mode, reduce_scatter=True) == \
                eb[f"grad_{mode}_rs"]
            # without error feedback the bound is additive in the hops
            assert codec.grad_error_bound(
                mode, error_feedback=False, hops=4) == \
                4 * eb[f"grad_{mode}"]
            # and the two-hop rs entries are the two-hop composition
            assert eb[f"grad_{mode}_rs"] == pytest.approx(
                2 * eb[f"grad_{mode}"])

    def test_grad_eligible_gating(self):
        assert codec.grad_eligible((256, 256), np.float32, "int8",
                                   min_bytes=1024)
        assert not codec.grad_eligible((4,), np.float32, "int8",
                                       min_bytes=1024)
        assert not codec.grad_eligible((256, 256), np.int32, "int8",
                                       min_bytes=0)
        assert not codec.grad_eligible((256, 256), np.float32, "nope",
                                       min_bytes=0)
        # default floor comes from the knob
        global_config.grad_quantize_min_bytes = 1 << 30
        assert not codec.grad_eligible((256, 256), np.float32, "int8")


def _train(method, n_steps=2, batch_size=16, hidden_dim=64):
    state, batch = create_mlp_train_state_and_batch(
        batch_size, hidden_dim=hidden_dim)
    step = get_mlp_train_step(method, use_value_and_grad=True)
    for _ in range(n_steps):
        state, loss = step(state, batch)
    return state, loss, step.get_last_executable()


def _state_bytes():
    state, _ = create_mlp_train_state_and_batch(16, hidden_dim=64)
    return sum(np.prod(a.shape) * a.dtype.itemsize
               for a in jax.tree_util.tree_leaves(state)
               if hasattr(a, "shape") and a.shape)


def _gq_counter(mode):
    from alpa_tpu.telemetry import metrics as _tmetrics
    fam = _tmetrics.get_registry().get("alpa_grad_quantized_tensors_total")
    return fam.labels(mode).value if fam else 0.0


class TestCostModeledChoice:
    """The ILP chooses quantized-vs-full per gradient tensor on net
    cost; ``grad_quantize_min_bytes`` flips exactly the tensors above
    the line, and the plan-time counters record each choice."""

    def test_budget_flips_tensors_to_quantized_reduce_scatter(self):
        alpa_tpu.init("local")
        tight = int(_state_bytes() * 0.66)
        _, loss_base, ex_base = _train(ShardParallel(
            auto_sharding_option=AutoShardingOption(
                memory_budget_per_device=tight)))

        global_config.grad_quantize = "int8"
        global_config.grad_quantize_min_bytes = 1024
        before = _gq_counter("int8")
        from alpa_tpu.telemetry import metrics as _tmetrics
        saved_fam = _tmetrics.get_registry().get(
            "alpa_grad_quantized_bytes_saved_total")
        saved_before = saved_fam.value if saved_fam else 0.0
        _, loss_q, ex_q = _train(ShardParallel(
            auto_sharding_option=AutoShardingOption(
                memory_budget_per_device=tight)))
        n_flipped = _gq_counter("int8") - before
        assert n_flipped >= 1, "no tensor chose the quantized variant"
        saved_fam = _tmetrics.get_registry().get(
            "alpa_grad_quantized_bytes_saved_total")
        assert saved_fam is not None and saved_fam.value > saved_before
        # the choice is a pricing/wire decision, not a layout change:
        # same shardings, bitwise-identical losses
        np.testing.assert_array_equal(np.asarray(loss_base),
                                      np.asarray(loss_q))

    def test_min_bytes_draws_the_per_tensor_line(self):
        alpa_tpu.init("local")
        tight = int(_state_bytes() * 0.66)
        global_config.grad_quantize = "int8"
        # hidden_dim=64: kernels are 16 KiB, biases 256 B — a floor
        # between the two quantizes only the kernels
        global_config.grad_quantize_min_bytes = 8192
        before = _gq_counter("int8")
        _train(ShardParallel(auto_sharding_option=AutoShardingOption(
            memory_budget_per_device=tight)))
        mid = _gq_counter("int8")
        assert mid > before
        # a floor above every leaf: no tensor may flip
        global_config.grad_quantize_min_bytes = 1 << 30
        _train(ShardParallel(auto_sharding_option=AutoShardingOption(
            memory_budget_per_device=tight)))
        assert _gq_counter("int8") == mid


class TestByteIdentityAtOff:
    """Default knobs must be invisible: bitwise losses, identical
    fingerprints, no cache-key token."""

    def test_defaults_are_bitwise_and_fingerprint_identical(self):
        alpa_tpu.init("local")
        assert global_config.grad_quantize == "off"
        _, loss_a, ex_a = _train(Zero2Parallel(num_micro_batches=2))
        global_config.grad_quantize = "off"       # explicit == default
        global_config.grad_error_feedback = True
        _, loss_b, ex_b = _train(Zero2Parallel(num_micro_batches=2))
        np.testing.assert_array_equal(np.asarray(loss_a),
                                      np.asarray(loss_b))
        assert ex_a.get_plan_fingerprint() == ex_b.get_plan_fingerprint()

    def test_cache_token_only_exists_when_on(self):
        from alpa_tpu.shard_parallel.solver import \
            _grad_quantize_cache_token
        assert _grad_quantize_cache_token() is None
        global_config.grad_quantize = "int8"
        tok = _grad_quantize_cache_token()
        assert tok is not None and "int8" in tok
        global_config.grad_error_feedback = False
        assert _grad_quantize_cache_token() != tok


class TestQuantizedBitPath:
    """ZeRO-2 + micro-batched accumulation through the quantized
    grad-accum scan: the bit path really changes, and stays within the
    certified bound's ballpark on the loss."""

    def test_zero2_quantized_grad_acc_close_but_not_bitwise(self):
        alpa_tpu.init("local")
        _, loss_ref, _ = _train(Zero2Parallel(num_micro_batches=2),
                                n_steps=3)
        global_config.grad_quantize = "int8"
        global_config.grad_quantize_min_bytes = 0
        _, loss_q, _ = _train(Zero2Parallel(num_micro_batches=2),
                              n_steps=3)
        # stochastic rounding moved the bits...
        assert np.asarray(loss_q) != np.asarray(loss_ref)
        # ...but the training math stayed sound
        np.testing.assert_allclose(np.asarray(loss_q),
                                   np.asarray(loss_ref),
                                   rtol=0.05, atol=1e-3)

    def test_error_feedback_off_still_trains(self):
        alpa_tpu.init("local")
        _, loss_ref, _ = _train(Zero2Parallel(num_micro_batches=2))
        global_config.grad_quantize = "int8"
        global_config.grad_quantize_min_bytes = 0
        global_config.grad_error_feedback = False
        _, loss_q, _ = _train(Zero2Parallel(num_micro_batches=2))
        np.testing.assert_allclose(np.asarray(loss_q),
                                   np.asarray(loss_ref),
                                   rtol=0.05, atol=1e-3)


# ---------------------------------------------------------------------
# pipeshard certification: composed bound, budget gate, warm restart
# ---------------------------------------------------------------------

def _compile_pipeshard():
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.pipeline_parallel.layer_construction import (
        ManualLayerOption)
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    alpa_tpu.init("local")
    method = PipeshardParallel(
        num_micro_batches=2,
        layer_option=ManualLayerOption(),
        stage_option=UniformStageOption(num_stages=2),
        default_auto_sharding_option=AutoShardingOption(zero_stage="0"))
    state, batch = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    step = get_mlp_train_step(method, use_value_and_grad=True)
    state, loss = step(state, batch)
    return step.get_last_executable(), state, batch, step


class TestCertifiedLaunch:

    def test_verdict_composes_nontrivial_gradient_bound(self):
        global_config.grad_quantize = "int8"
        global_config.grad_quantize_min_bytes = 0
        ex, *_ = _compile_pipeshard()
        v = ex.get_plan_verdict()
        st = v.stats["numerics"]
        per_hop = codec.ERROR_BOUND["grad_int8"]
        assert st["lossy_edges"].get("grad_int8", 0) >= 1, st
        # non-trivial (the gradient path really composed hops), an
        # exact multiple of the documented per-hop bound, and certified
        # under the default budget
        assert st["max_error_bound"] >= per_hop
        n_hops = st["max_error_bound"] / per_hop
        assert n_hops == pytest.approx(round(n_hops))
        assert st["max_error_bound"] <= global_config.numerics_error_budget
        assert v.ok, v.format_table()
        # the rendered numerics.txt names the gradient hop
        from alpa_tpu.analysis import numerics as num
        text = num.format_numerics(st, v.findings())
        assert "grad_int8" in text

    def test_perf_gate_pins_certified_bound_and_committed_results(self):
        """Tier-1 arm of the ISSUE 19 perf gate: recompute the
        deterministic certified bound live, take the wire ratio and the
        loss-curve deltas from the committed bench results, and hold
        all of them against the ``gradquant.*`` baselines."""
        import json
        import os
        global_config.grad_quantize = "int8"
        global_config.grad_quantize_min_bytes = 0
        ex, *_ = _compile_pipeshard()
        bound = ex.get_plan_verdict().stats["numerics"]["max_error_bound"]

        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        with open(os.path.join(repo, "benchmark", "results",
                               "grad_quant.json"), encoding="utf-8") as f:
            committed = json.load(f)
        fresh = dict(committed["gate_metrics"])
        fresh["gradquant.certified_bound"] = float(bound)

        from benchmark.perf_gate import gate
        gv = gate(fresh)
        checked = {c["metric"] for c in gv["checks"]}
        assert {"gradquant.certified_bound",
                "gradquant.wire_ratio_int8",
                "gradquant.loss_delta_int8"} <= checked, checked
        assert gv["pass"], gv
        # the acceptance floor: >= 3x fewer wire bytes under int8
        assert fresh["gradquant.wire_ratio_int8"] >= 3.0

    def test_shrunk_budget_blocks_launch(self):
        from alpa_tpu.analysis import plan_verifier as pv
        global_config.grad_quantize = "int8"
        global_config.grad_quantize_min_bytes = 0
        ex, state, batch, step = _compile_pipeshard()
        bound = ex.get_plan_verdict().stats["numerics"]["max_error_bound"]
        global_config.numerics_error_budget = bound * 0.5
        global_config.verify_plans_numerics = "error"
        ex._register_programs = {}
        ex._register_program = None
        try:
            with pytest.raises(pv.PlanVerificationError) as exc_info:
                step(state, batch)
            assert "numerics.budget-exceeded" in str(exc_info.value)
        finally:
            ex._register_programs = {}
            ex._register_program = None

    def test_warm_restart_identical_fingerprint_zero_solves(self, tmp_path):
        from alpa_tpu.compile_cache import (get_compile_cache,
                                            reset_compile_cache)
        alpa_tpu.init("local")
        global_config.compile_cache_dir = str(tmp_path)
        global_config.grad_quantize = "int8"
        global_config.grad_quantize_min_bytes = 1024
        reset_compile_cache()
        # the auto-sharding ILP path is the one whose cache key carries
        # the gq: token (Zero2Parallel plans rule-based, no solve)
        tight = int(_state_bytes() * 0.66)
        method = lambda: ShardParallel(  # noqa: E731
            auto_sharding_option=AutoShardingOption(
                memory_budget_per_device=tight))
        _, loss_cold, ex_cold = _train(method())
        fp_cold = ex_cold.get_plan_fingerprint()
        # warm restart: drop the memory tier, replan from disk
        reset_compile_cache()
        _, loss_warm, ex_warm = _train(method())
        assert ex_warm.get_plan_fingerprint() == fp_cold
        stats = get_compile_cache().stats()["namespaces"].get("ilp", {})
        assert stats.get("hits", 0) >= 1, stats
        np.testing.assert_array_equal(np.asarray(loss_cold),
                                      np.asarray(loss_warm))
