"""Auto-sharding ILP planner: structural assertions on chosen strategies.

Mirrors the reference's strategy-assert tests (SURVEY.md §4.2: "expected
DP/TP/ZeRO choices on MLP/Bert, collective counting on HLO text").
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu import AutoShardingOption, ShardParallel
from alpa_tpu.testing import (assert_allclose, create_mlp_train_state_and_batch,
                              get_mlp_train_step, skip_if_old_jax)
from alpa_tpu.util import count_communication_primitives


def _train_and_get_executable(bs, hidden, method):
    state, batch = create_mlp_train_state_and_batch(batch_size=bs,
                                                    input_dim=hidden,
                                                    hidden_dim=hidden,
                                                    output_dim=hidden)
    ref_state, _ = create_mlp_train_state_and_batch(batch_size=bs,
                                                    input_dim=hidden,
                                                    hidden_dim=hidden,
                                                    output_dim=hidden)
    step = get_mlp_train_step(method, use_value_and_grad=True)
    serial = get_mlp_train_step(None)
    s1, _ = step(state, batch)
    s0, _ = serial(ref_state, batch)
    assert_allclose(jax.device_get(s0.params), jax.device_get(s1.params),
                    2e-3, 2e-3)
    return step.get_last_executable()


def _batch_arg_specs(ex, bs):
    return [
        s.spec for s, a in zip(ex.in_shardings, ex.in_avals)
        if len(a.shape) == 2 and a.shape[0] == bs
    ]


def _param_specs(ex, bs):
    return [
        s.spec for s, a in zip(ex.in_shardings, ex.in_avals)
        if len(a.shape) == 2 and a.shape[0] != bs
    ]


class TestAutoShardingChoices:

    def test_large_batch_chooses_data_parallel(self):
        ex = _train_and_get_executable(2048, 32, ShardParallel())
        x_specs = _batch_arg_specs(ex, 2048)
        # batch dim (dim 0) sharded on at least one batch arg
        assert any(len(s) >= 1 and s[0] is not None for s in x_specs), x_specs
        # params replicated
        assert all(all(p is None for p in s) for s in _param_specs(ex, 2048))

    def test_wide_model_chooses_tensor_parallel(self):
        ex = _train_and_get_executable(8, 2048, ShardParallel())
        p_specs = _param_specs(ex, 8)
        # weight matrices sharded on at least one dim
        assert any(any(p is not None for p in s) for s in p_specs), p_specs

    def test_forced_mesh_shape(self):
        method = ShardParallel(auto_sharding_option=AutoShardingOption(
            logical_mesh_shape=(8, 1)))
        ex = _train_and_get_executable(64, 64, method)
        assert ex is not None

    def test_force_batch_dim_mapping(self):
        method = ShardParallel(auto_sharding_option=AutoShardingOption(
            force_batch_dim_to_mesh_dim=0, logical_mesh_shape=(8, 1)))
        ex = _train_and_get_executable(64, 64, method)
        x_specs = _batch_arg_specs(ex, 64)
        assert any(s and s[0] == "mesh0" for s in x_specs), x_specs

    def test_solver_handles_big_jaxpr(self):
        # A deeper MLP: planner must stay fast and correct.
        state, batch = create_mlp_train_state_and_batch(batch_size=256,
                                                        num_layers=8)
        step = get_mlp_train_step(ShardParallel(), use_value_and_grad=True)
        s1, loss = step(state, batch)
        assert np.isfinite(float(loss))


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestConstraintEmission:

    def test_constrained_eval_shared_subjaxprs(self):
        """jax caches traced sub-jaxprs: two relu calls share inner Vars.
        The flattened evaluator must freshen per inline site (regression:
        second site overwrote the first's values)."""
        from jax.extend.core import Literal

        from alpa_tpu.shard_parallel.strategy import (_subst,
                                                      flatten_jaxpr_eqns)

        def f(x, w1, b1, w2, b2):
            h1 = jax.nn.relu(x @ w1 + b1)
            h2 = jax.nn.relu(h1 @ w2 + b2)
            return h1, h1 > 0, h2, h2 > 0

        avals = [
            jax.ShapeDtypeStruct(s, jnp.float32)
            for s in [(4, 8), (8, 8), (8,), (8, 8), (8,)]
        ]
        cj = jax.make_jaxpr(f)(*avals)
        info = {}
        flat = flatten_jaxpr_eqns(cj.jaxpr, info=info)
        rs = np.random.RandomState(0)
        args = [jnp.asarray(rs.randn(*a.shape).astype(np.float32))
                for a in avals]
        want = f(*args)
        env = dict(zip(cj.jaxpr.invars, args))
        env.update(zip(cj.jaxpr.constvars, cj.consts))
        env.update(info["captured_consts"])

        def read(v):
            return v.val if isinstance(v, Literal) else env[v]

        for e in flat:
            if e.primitive.name == "pipeline":
                for iv, ov in zip(e.invars, e.outvars):
                    env[ov] = read(iv)
                continue
            vals = [read(v) for v in e.invars]
            ans = e.primitive.bind(*vals, **e.params)
            if not e.primitive.multiple_results:
                ans = [ans]
            for ov, a in zip(e.outvars, ans):
                env[ov] = a
        for i, v in enumerate(cj.jaxpr.outvars):
            got = env[_subst(v, info["env"])]
            np.testing.assert_array_equal(
                np.asarray(want[i]), np.asarray(got))

    def test_emission_observable_and_correct(self):
        from alpa_tpu import AutoShardingOption

        ex_on = _train_and_get_executable(
            8, 2048,
            ShardParallel(auto_sharding_option=AutoShardingOption(
                emit_sharding_constraints=True)))
        ex_off = _train_and_get_executable(
            8, 2048,
            ShardParallel(auto_sharding_option=AutoShardingOption(
                emit_sharding_constraints=False)))
        assert ex_on is not None and ex_off is not None

    def test_memory_budget_forces_sharding(self):
        """A per-device byte budget makes the ILP shard more inputs than
        the unconstrained plan (ref memory_budget_per_device)."""
        from alpa_tpu import AutoShardingOption

        def count_nonreplicated_params(budget):
            state, batch = create_mlp_train_state_and_batch(
                batch_size=2048, input_dim=64, hidden_dim=64, output_dim=64)
            opt = (AutoShardingOption(memory_budget_per_device=budget)
                   if budget else AutoShardingOption())
            step = get_mlp_train_step(
                ShardParallel(auto_sharding_option=opt),
                use_value_and_grad=True)
            step(state, batch)
            ex = step.get_last_executable()
            # params only: batch inputs shard under plain DP anyway (the
            # planner's data-parallel tie preference)
            return sum(1 for s, a in zip(ex.in_shardings, ex.in_avals)
                       if a.shape[:1] != (2048,) and
                       str(s.spec) != "PartitionSpec()")

        assert (count_nonreplicated_params(150_000) >
                count_nonreplicated_params(None))

    def test_remat_survives_constraint_emission(self):
        """Constraint emission used to be skipped whenever remat was
        present; now the constrained function re-wraps checkpoint bodies,
        so remat2 AND sharding_constraint coexist in the traced jaxpr."""
        from alpa_tpu.device_mesh import get_global_cluster
        from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
        from alpa_tpu.shard_parallel.solver import plan_auto_sharding

        alpa_tpu.init("local")
        mesh = get_global_cluster().get_physical_mesh()
        D = 512

        def fn(w1, w2, x):

            @jax.checkpoint
            def blk(x):
                return jnp.tanh(x @ w1)

            h = blk(x)
            return jax.grad(lambda w: jnp.tanh(h @ w).sum())(w2)

        avals = [
            jax.ShapeDtypeStruct((D, D), jnp.float32),
            jax.ShapeDtypeStruct((D, D), jnp.float32),
            jax.ShapeDtypeStruct((8, D), jnp.float32),
        ]
        _, in_sh, cfn, _ = plan_auto_sharding(fn, avals, ["w1", "w2", "x"],
                                              [2], mesh,
                                              AutoShardingOption())
        assert cfn is not None

        def prims(jx, acc):
            for e in jx.eqns:
                acc.append(e.primitive.name)
                for v in e.params.values():
                    if hasattr(v, "jaxpr"):
                        prims(v.jaxpr, acc)
                    elif hasattr(v, "eqns"):
                        prims(v, acc)
            return acc

        allp = prims(jax.make_jaxpr(cfn)(*avals).jaxpr, [])
        assert "remat2" in allp, set(allp)
        assert "sharding_constraint" in allp, set(allp)
        rs = np.random.RandomState(0)
        args = [jnp.asarray(rs.randn(*a.shape).astype(np.float32))
                for a in avals]
        want = fn(*args)
        got = cfn(*args)[0]
        np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                                   rtol=1e-5, atol=1e-5)

    @skip_if_old_jax("compiled HLO spells the planned TP collectives "
                     "differently, so count_communication_primitives "
                     "finds none of the expected all-reduces")
    def test_ilp_choice_realized_in_hlo_gpt(self):
        """Fidelity: the all-reduces in compiled HLO equal the comm-bearing
        strategies the ILP chose (planner choice == HLO reality)."""
        from alpa_tpu.device_mesh import get_global_cluster
        from alpa_tpu.model.gpt_model import GPTConfig, TransformerBlock
        from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
        from alpa_tpu.shard_parallel.solver import plan_auto_sharding

        alpa_tpu.init("local")
        mesh = get_global_cluster().get_physical_mesh()
        cfg = GPTConfig(hidden_size=512, num_layers=1, num_heads=8,
                        seq_len=64, vocab_size=256)
        block = TransformerBlock(cfg)
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (4, 64, 512))
        params = block.init(rng, x)
        flat, tree = jax.tree_util.tree_flatten((params, x))
        avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in flat]

        def flat_fn(*leaves):
            p, xx = jax.tree_util.tree_unflatten(tree, leaves)
            out, _ = block.apply(p, xx)
            return out

        opt = AutoShardingOption(logical_mesh_shape=(1, 8),
                                 constrain_min_elements=0)
        batch_idx = [i for i, a in enumerate(flat) if a.shape[:1] == (4,)]
        _, in_sh, cfn, _, (graph, choice) = plan_auto_sharding(
            flat_fn, avals, [""] * len(avals), batch_idx, mesh, opt,
            return_graph=True)
        assert cfn is not None
        planned = sum(1 for n, s in zip(graph.nodes, choice)
                      if n.kind == "op" and n.outvar is not None and
                      n.strategies[s].comm_cost > 0)
        assert planned >= 1  # shapes chosen so TP-style comm is planned
        hlo = jax.jit(cfn, in_shardings=in_sh).lower(*avals).compile() \
            .as_text()
        _, n_ar, _, _, _ = count_communication_primitives(hlo)
        assert n_ar == planned, (planned, n_ar)

    def test_ilp_choice_realized_in_hlo_conv(self):
        """Conv analog of the GPT fidelity test, on a compact conv tower
        (GSPMD retains some realization freedom on full WResNet — same-
        cost all-gather realizations — so the deterministic assertion
        lives on a small tower; WResNet coverage is the planner test
        below)."""
        from flax import linen as nn

        from alpa_tpu.device_mesh import get_global_cluster
        from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
        from alpa_tpu.shard_parallel.solver import plan_auto_sharding

        alpa_tpu.init("local")
        mesh = get_global_cluster().get_physical_mesh()

        class Tower(nn.Module):

            @nn.compact
            def __call__(self, x):
                x = nn.Conv(256, (3, 3), use_bias=False)(x)
                x = nn.relu(x)
                x = nn.Conv(256, (3, 3), use_bias=False)(x)
                x = nn.relu(x)
                return nn.Conv(256, (1, 1), use_bias=False)(x)

        model = Tower()
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (2, 16, 16, 256))
        params = model.init(rng, x)
        flat, tree = jax.tree_util.tree_flatten((params, x))
        avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in flat]

        def flat_fn(*leaves):
            p, xx = jax.tree_util.tree_unflatten(tree, leaves)
            return model.apply(p, xx)

        opt = AutoShardingOption(logical_mesh_shape=(1, 8),
                                 constrain_min_elements=0)
        batch_idx = [i for i, a in enumerate(flat)
                     if a.shape[:1] == (2,) and len(a.shape) == 4]
        _, in_sh, cfn, _, (graph, choice) = plan_auto_sharding(
            flat_fn, avals, [""] * len(avals), batch_idx, mesh, opt,
            return_graph=True)
        chosen = [n.strategies[s] for n, s in zip(graph.nodes, choice)
                  if n.kind == "op" and n.outvar is not None and
                  n.strategies[s].comm_cost > 0]
        planned_ar = sum(1 for st in chosen
                         if st.comm_kind == "all_reduce")
        planned_halo = sum(1 for st in chosen
                           if st.comm_kind == "ppermute")
        if cfn is None:
            assert not chosen
            return
        hlo = jax.jit(cfn, in_shardings=in_sh).lower(*avals).compile() \
            .as_text()
        _, n_ar, _, _, _ = count_communication_primitives(hlo)
        assert n_ar == planned_ar, (planned_ar, n_ar)
        if planned_halo:
            assert "collective-permute" in hlo, \
                "halo strategies chosen but no halo exchange in HLO"

    def test_conv_spatial_halo_strategy(self):
        """When batch and channels cannot shard (indivisible), the conv
        planner must fall back to spatial sharding — GSPMD realizes it as
        a halo exchange (VERDICT r1 weak#8 / next#9)."""
        import flax.linen as nn

        from alpa_tpu.device_mesh import get_global_cluster
        from alpa_tpu.shard_parallel.solver import plan_auto_sharding

        alpa_tpu.init(cluster="local")
        mesh = get_global_cluster().get_physical_mesh()

        class SpatialNet(nn.Module):

            @nn.compact
            def __call__(self, x):
                # batch 1 (indivisible), channels 3->5 (indivisible by 8):
                # only the 64-long spatial dims can shard
                x = nn.Conv(5, (3, 3), use_bias=False)(x)
                return nn.relu(x)

        model = SpatialNet()
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (1, 64, 64, 3))
        params = model.init(rng, x)
        flat, tree = jax.tree_util.tree_flatten((params, x))
        avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in flat]

        def flat_fn(*leaves):
            p, xx = jax.tree_util.tree_unflatten(tree, leaves)
            return model.apply(p, xx)

        opt = AutoShardingOption(logical_mesh_shape=(1, 8),
                                 constrain_min_elements=0)
        _, in_sh, cfn, _, (graph, choice) = plan_auto_sharding(
            flat_fn, avals, [""] * len(avals), [], mesh, opt,
            return_graph=True)
        halo = [n.strategies[s].name for n, s in zip(graph.nodes, choice)
                if n.kind == "op" and "'s'" in n.strategies[s].name]
        assert halo, "no spatial (halo) conv strategy chosen"
        # the compiled program realizes the halo via collective-permute
        fn = cfn if cfn is not None else flat_fn
        hlo = jax.jit(fn, in_shardings=in_sh).lower(*avals).compile() \
            .as_text()
        assert "collective-permute" in hlo, \
            "spatial sharding chosen but no halo exchange emitted"

    def test_grouped_conv_group_sharding(self):
        """Grouped (depthwise-style) convs get the group role 'g': whole
        channel groups shard with no collective."""
        from alpa_tpu.device_mesh import get_global_cluster
        from alpa_tpu.shard_parallel.strategy import (
            enumerate_conv_strategies)

        alpa_tpu.init(cluster="local")
        mesh = get_global_cluster().get_physical_mesh()
        lm = mesh.get_logical_mesh((1, 8))

        def probe(x, w):
            return jax.lax.conv_general_dilated(
                x, w, (1, 1), "SAME", feature_group_count=8,
                dimension_numbers=("NHWC", "HWIO", "NHWC"))

        x = jax.ShapeDtypeStruct((2, 8, 8, 32), jnp.float32)
        w = jax.ShapeDtypeStruct((3, 3, 4, 32), jnp.float32)
        jaxpr = jax.make_jaxpr(probe)(x, w)
        conv_eqn = [e for e in jaxpr.jaxpr.eqns
                    if e.primitive.name == "conv_general_dilated"][0]
        sts = enumerate_conv_strategies(conv_eqn, lm)
        names = {st.name for st in sts}
        assert any("'g'" in n for n in names), names
        g = [st for st in sts if "'g'" in st.name][0]
        assert g.comm_cost == 0.0, "group sharding needs no collective"

    @pytest.mark.slow
    def test_wresnet_conv_planner_chooses_parallelism(self):
        """Convolutions get real strategies (batch/channel roles), not
        replication barriers: the planner must shard the image batch."""
        import optax
        from flax.training import train_state

        from alpa_tpu.model.wide_resnet import WResNetConfig, WideResNet

        cfg = WResNetConfig(num_layers=50, width_factor=1, num_classes=10)
        model = WideResNet(cfg)
        rng = jax.random.PRNGKey(0)
        x = jax.random.normal(rng, (16, 32, 32, 3))
        y = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 10)
        state = train_state.TrainState.create(apply_fn=model.apply,
                                              params=model.init(rng, x),
                                              tx=optax.sgd(1e-2))

        def step_fn(state, batch):

            def loss_fn(p):
                logits = state.apply_fn(p, batch["x"])
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, batch["y"]).mean()

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads=grads), loss

        pstep = alpa_tpu.parallelize(step_fn, method=ShardParallel())
        serial = jax.jit(step_fn)
        _, lp = pstep(state, {"x": x, "y": y})

        state2 = train_state.TrainState.create(apply_fn=model.apply,
                                               params=model.init(rng, x),
                                               tx=optax.sgd(1e-2))
        _, ls = serial(state2, {"x": x, "y": y})
        assert_allclose(float(lp), float(ls), 1e-3, 1e-3)
        ex = pstep.get_last_executable()
        # the planner must produce a genuinely parallel program: the
        # model/optimizer state or activations shard across the mesh
        # (which exact conv role wins — batch vs channel — is a cost-model
        # tie; both are valid parallelism)
        sharded_inputs = sum(
            1 for s, a in zip(ex.in_shardings, ex.in_avals)
            if len(a.shape) >= 1 and any(
                p is not None for p in s.spec))
        assert sharded_inputs > 0, "everything replicated"
        total, n_ar, n_ag, n_rs, _ = count_communication_primitives(
            ex.get_hlo_text())
        assert total > 0, "no collectives: not parallel"
