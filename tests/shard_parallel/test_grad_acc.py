"""Gradient accumulation correctness (ref test strategy SURVEY.md §4.2).

Oracle: num_micro_batches=N must produce the same updated state as the
full-batch step (mean-loss semantics make microbatch-mean averaging exact).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu import DataParallel, ShardParallel
from alpa_tpu.testing import (assert_allclose, create_mlp_train_state_and_batch,
                              get_mlp_train_step)
from alpa_tpu.util import count_communication_primitives


class TestGradAccumulation:

    def _compare(self, method, rtol=1e-3):
        state_a, batch = create_mlp_train_state_and_batch(batch_size=64)
        # Independent buffers: donation consumes inputs.
        state_b, _ = create_mlp_train_state_and_batch(batch_size=64)
        full_step = get_mlp_train_step(ShardParallel(), use_value_and_grad=True)
        acc_step = get_mlp_train_step(method, use_value_and_grad=True)
        for _ in range(2):
            state_a, loss_a = full_step(state_a, batch)
            state_b, loss_b = acc_step(state_b, batch)
        assert_allclose(float(loss_a), float(loss_b), rtol, rtol)
        assert_allclose(jax.device_get(state_a.params),
                        jax.device_get(state_b.params), rtol, rtol)
        return acc_step.get_last_executable()

    def test_grad_acc_matches_full_batch(self):
        self._compare(ShardParallel(num_micro_batches=4))

    def test_grad_acc_data_parallel(self):
        executable = self._compare(DataParallel(num_micro_batches=4))
        # The scan must NOT contain a per-microbatch all-reduce: gradient
        # sync happens once per step (the TPU analog of the reference's
        # skip-allreduce trick, SURVEY.md §2.9).
        hlo = executable.get_hlo_text()
        total, n_ar, *_ = count_communication_primitives(hlo)
        # One grad all-reduce per gradient leaf outside the loop is fine; a
        # while-loop body with collectives would show up as many more.
        assert n_ar <= 8, f"too many all-reduces ({n_ar}): sync inside scan?"

    def test_grad_acc_requires_marker(self):
        state, batch = create_mlp_train_state_and_batch()

        @alpa_tpu.parallelize(method=ShardParallel(num_micro_batches=2))
        def bad_step(state, batch):

            def loss_fn(p):
                out = state.apply_fn(p, batch["x"])
                return jnp.mean((out - batch["y"])**2)

            grads = jax.grad(loss_fn)(state.params)  # plain jax.grad: no marker
            return state.apply_gradients(grads=grads)

        with pytest.raises(ValueError, match="gradient boundary"):
            bad_step(state, batch)

    def test_indivisible_microbatch_errors(self):
        state, batch = create_mlp_train_state_and_batch(batch_size=6)
        step = get_mlp_train_step(ShardParallel(num_micro_batches=4),
                                  use_value_and_grad=True)
        with pytest.raises(ValueError, match="not divisible"):
            step(state, batch)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
