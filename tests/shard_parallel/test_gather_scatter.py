"""Gather/scatter/dynamic_update_slice sharding strategies (VERDICT r2
missing #1): vocab-sharded embedding tables and in-place KV-cache updates
must participate in the ILP instead of falling to unknown-op replication.

Role analog: the reference's C++ pass enumerates strategies for the full
HLO instruction set including gather/scatter (readable spec in ref
playground/auto_sharding_solver/solver.py).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu.shard_parallel.auto_sharding import AutoShardingOption
from alpa_tpu.shard_parallel.strategy import (build_strategy_graph,
                                              enumerate_gather_strategies,
                                              enumerate_scatter_strategies,
                                              flatten_jaxpr_eqns)
from alpa_tpu.testing import assert_allclose


def _logical_mesh(shape):
    from alpa_tpu.device_mesh import LogicalDeviceMesh
    n = int(np.prod(shape))
    return LogicalDeviceMesh(None, np.arange(n).reshape(shape),
                             mesh_beta=(0.1, 0.01))


def _find_eqn(fn, args, prim):
    jx = jax.make_jaxpr(fn)(*args)
    for e in flatten_jaxpr_eqns(jx.jaxpr):
        if e.primitive.name == prim:
            return e
    raise AssertionError(f"no {prim} eqn found")


class TestGatherStrategies:

    def test_embedding_roles(self):
        """The gather node offers index-batch, feature (passthrough) and
        vocab-parallel (all-reduce) shardings of an embedding lookup."""
        table = jnp.zeros((1024, 64))
        ids = jnp.zeros((8, 16), jnp.int32)
        eqn = _find_eqn(lambda t, i: jnp.take(t, i, axis=0), (table, ids),
                        "gather")
        mesh = _logical_mesh((1, 8))
        sts = enumerate_gather_strategies(eqn, mesh)
        by_name = {s.name: s for s in sts}
        # vocab-parallel: operand dim 0 sharded, output replicated, comm > 0
        vocab = [s for s in sts if s.operand_specs[0][0] and
                 not any(s.out_spec)]
        assert vocab and all(s.comm_cost > 0 for s in vocab), by_name
        # feature-parallel: operand dim 1 sharded -> out last dim, free
        feat = [s for s in sts if s.operand_specs[0][1] and
                s.out_spec[-1] and s.comm_cost == 0]
        assert feat, by_name
        # index-batch: indices dim 0 sharded -> out dim 0, free
        ib = [s for s in sts if s.operand_specs[1][0] and s.out_spec[0] and
              s.comm_cost == 0]
        assert ib, by_name

    def test_scatter_add_roles(self):
        """The embedding-gradient scatter-add offers window, scattered-dim
        (vocab) and update-batch (all-reduce) shardings."""
        table = jnp.zeros((1024, 64))
        ids = jnp.zeros((8, 16), jnp.int32)
        eqn = _find_eqn(
            jax.grad(lambda t, i: jnp.take(t, i, axis=0).sum()),
            (table, ids), "scatter-add")
        mesh = _logical_mesh((1, 8))
        sts = enumerate_scatter_strategies(eqn, mesh)
        # vocab-parallel table grad: operand dim 0 sharded, free
        sc = [s for s in sts if s.out_spec[0] and s.comm_cost == 0]
        assert sc, [s.name for s in sts]
        # update-batch sharded: partial tables all-reduce
        ub = [s for s in sts if s.operand_specs[2][0] and s.comm_cost > 0]
        assert ub, [s.name for s in sts]
        # window (feature) dim: operand + updates shard together, free
        w = [s for s in sts if s.out_spec[1] and s.operand_specs[2][-1] and
             s.comm_cost == 0]
        assert w, [s.name for s in sts]


class TestEndToEnd:

    def test_vocab_parallel_embedding_chosen(self):
        """With the feature dim indivisible by the mesh and a memory budget
        that forbids replicating the table, the ILP picks the vocab-
        parallel gather strategy (table sharded on dim 0) and the
        constrained function still computes the exact lookup."""
        from alpa_tpu.device_mesh import get_global_cluster
        from alpa_tpu.shard_parallel.solver import plan_auto_sharding

        alpa_tpu.init("local")
        mesh = get_global_cluster().get_physical_mesh()
        V, H = 4096, 100  # H % 8 != 0: feature sharding is invalid
        table = jnp.arange(V * H, dtype=jnp.float32).reshape(V, H) / (V * H)
        ids = jnp.arange(32, dtype=jnp.int32).reshape(4, 8) * 7

        def fn(t, i):
            return jnp.take(t, i, axis=0) * 2.0

        avals = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in (table, ids)]
        # budget: a full table replica (1.6 MB) must not fit
        opt = AutoShardingOption(logical_mesh_shape=(1, 8),
                                 memory_budget_per_device=600_000,
                                 constrain_min_elements=0)
        jax_mesh, in_sh, cfn, _, (graph, choice) = plan_auto_sharding(
            fn, avals, ["", ""], [1], mesh, opt, return_graph=True)
        table_spec = None
        for node, s in zip(graph.nodes, choice):
            if node.kind == "invar" and node.invar_idx == 0:
                table_spec = node.strategies[s].out_spec
        assert table_spec is not None and table_spec[0], (
            f"table not vocab-sharded: {table_spec}")
        (out,) = jax.jit(cfn, in_shardings=in_sh)(table, ids)
        assert_allclose(np.asarray(out), np.asarray(fn(table, ids)),
                        1e-6, 1e-6)

    def test_kv_cache_update_not_barriered(self):
        """dynamic_update_slice follows its cache operand: the strategy
        graph must not contain a replication barrier for it, and the
        planner output stays numerically exact."""
        from alpa_tpu.device_mesh import get_global_cluster
        from alpa_tpu.shard_parallel.solver import plan_auto_sharding

        alpa_tpu.init("local")
        mesh = get_global_cluster().get_physical_mesh()
        B, T, NH, D = 4, 32, 8, 16
        cache = jnp.zeros((B, T, NH, D))
        new_kv = jnp.ones((B, 1, NH, D))
        q = jnp.ones((B, NH, D))

        def fn(cache, new_kv, q):
            cache = jax.lax.dynamic_update_slice(cache, new_kv, (0, 5, 0, 0))
            scores = jnp.einsum("bhd,bthd->bht", q, cache)
            return cache, scores

        avals = [jax.ShapeDtypeStruct(a.shape, a.dtype)
                 for a in (cache, new_kv, q)]
        opt = AutoShardingOption(logical_mesh_shape=(1, 8),
                                 constrain_min_elements=0)
        _, in_sh, cfn, _, (graph, _) = plan_auto_sharding(
            fn, avals, [""] * 3, [0], mesh, opt, return_graph=True)
        barriers = [n.label for n in graph.nodes
                    if n.label == "barrier:dynamic_update_slice"]
        assert not barriers, barriers
        got_cache, got_scores = jax.jit(cfn, in_shardings=in_sh)(
            cache, new_kv, q)
        want_cache, want_scores = fn(cache, new_kv, q)
        assert_allclose(np.asarray(got_cache), np.asarray(want_cache),
                        1e-6, 1e-6)
        assert_allclose(np.asarray(got_scores), np.asarray(want_scores),
                        1e-5, 1e-5)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
