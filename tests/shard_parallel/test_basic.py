"""Shard-parallel correctness + structural tests.

Modeled on ref ``tests/shard_parallel/test_basic.py`` (SURVEY.md §4.2):
serial-vs-parallel equivalence via assert_allclose plus collective-counting
assertions on the compiled HLO.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import alpa_tpu
from alpa_tpu import (DataParallel, ShardParallel, Zero2Parallel,
                      Zero3Parallel)
from alpa_tpu.testing import (assert_allclose, create_mlp_train_state_and_batch,
                              get_mlp_train_step)
from alpa_tpu.util import count_communication_primitives


def _run_and_compare(method, n_steps=2, rtol=1e-3):
    state_serial, batch = create_mlp_train_state_and_batch()
    state_parallel = state_serial

    serial_step = get_mlp_train_step(None)
    parallel_step = get_mlp_train_step(method, use_value_and_grad=True)

    for _ in range(n_steps):
        state_serial, _ = serial_step(state_serial, batch)
        state_parallel, _ = parallel_step(state_parallel, batch)

    assert_allclose(jax.device_get(state_serial.params),
                    jax.device_get(state_parallel.params), rtol, rtol)
    return parallel_step.get_last_executable()


class TestShardParallelBasic:

    def test_data_parallel(self):
        executable = _run_and_compare(DataParallel())
        hlo = executable.get_hlo_text()
        # Pure DP: gradient sync -> at least one all-reduce, no all-gather.
        _, n_ar, n_ag, n_rs, _ = count_communication_primitives(hlo)
        assert n_ar >= 1, f"expected grad all-reduce, hlo has {n_ar}"

    def test_zero2(self):
        executable = _run_and_compare(Zero2Parallel())
        hlo = executable.get_hlo_text()
        total, n_ar, n_ag, n_rs, _ = count_communication_primitives(hlo)
        # ZeRO-2: sharded optimizer state => reduce-scatter (or AR+slice
        # before XLA's pattern match) + all-gather of updates.
        assert total >= 1

    def test_zero3(self):
        executable = _run_and_compare(Zero3Parallel())
        hlo = executable.get_hlo_text()
        total, n_ar, n_ag, n_rs, _ = count_communication_primitives(hlo)
        assert total >= 1

    def test_shard_parallel_auto(self):
        _run_and_compare(ShardParallel())

    def test_explicit_mesh_devices(self):
        devices = jax.devices()[:4]
        _run_and_compare(ShardParallel(devices=devices))

    def test_executable_introspection(self):
        executable = _run_and_compare(DataParallel())
        assert executable.get_total_allocation_size() != 0
        assert "HloModule" in executable.get_hlo_text()
        costs = executable.profile_with_dummy_inputs(repeat=2, number=1)
        assert np.all(costs > 0)


class TestInference:

    def test_forward_only(self):
        state, batch = create_mlp_train_state_and_batch()

        @alpa_tpu.parallelize(method=ShardParallel(), batch_argnums=(1,))
        def forward(state, batch):
            return state.apply_fn(state.params, batch["x"])

        out = forward(state, batch)
        expected = state.apply_fn(state.params, batch["x"])
        assert_allclose(np.asarray(out), np.asarray(expected))


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])


class TestManualSharding:

    def test_manual_in_specs_override_planner(self):
        from jax.sharding import PartitionSpec as P

        from alpa_tpu import ManualShardingOption, ShardParallel
        from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                      get_mlp_train_step)

        state, batch = create_mlp_train_state_and_batch(batch_size=64)
        # force the batch dict's leaves: x sharded on rows, y replicated
        ms = ManualShardingOption(
            in_axis_resources=(None, {"x": P("mesh0"), "y": P()}))
        method = ShardParallel(manual_sharding_option=ms)
        step = get_mlp_train_step(method, use_value_and_grad=True)
        s1, _ = step(state, batch)
        ex = step.get_last_executable()
        specs = [
            str(s.spec) for s, a in zip(ex.in_shardings, ex.in_avals)
            if len(a.shape) == 2 and a.shape[0] == 64
        ]
        assert "PartitionSpec('mesh0',)" in specs, specs
        assert "PartitionSpec()" in specs, specs
