"""Torch frontend: fx -> jax conversion parity with torch eager
(ref alpa/torch tests)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

import alpa_tpu
from alpa_tpu.torch_frontend import functionalize, set_mode


def _compare(module, *torch_inputs, rtol=1e-4):
    fn, params = functionalize(module)
    with torch.no_grad():
        expected = module(*torch_inputs).numpy()
    jax_inputs = [jnp.asarray(t.numpy()) for t in torch_inputs]
    got = np.asarray(fn(params, *jax_inputs))
    np.testing.assert_allclose(got, expected, rtol=rtol, atol=rtol)
    return fn, params, jax_inputs


class TestConversion:

    def test_mlp(self):
        m = torch.nn.Sequential(
            torch.nn.Linear(16, 32), torch.nn.ReLU(),
            torch.nn.Linear(32, 8), torch.nn.Softmax(dim=-1))
        _compare(m, torch.randn(4, 16))

    def test_functional_ops(self):

        class Net(torch.nn.Module):

            def __init__(self):
                super().__init__()
                self.fc = torch.nn.Linear(8, 8)

            def forward(self, x):
                h = torch.nn.functional.gelu(self.fc(x))
                h = h.transpose(0, 1).contiguous()
                h = h.view(-1)
                return (h * 2 + 1).mean()

        _compare(Net(), torch.randn(3, 8))

    def test_embedding_layernorm(self):

        class Net(torch.nn.Module):

            def __init__(self):
                super().__init__()
                self.emb = torch.nn.Embedding(32, 16)
                self.ln = torch.nn.LayerNorm(16)
                self.head = torch.nn.Linear(16, 4)

            def forward(self, ids):
                return self.head(self.ln(self.emb(ids)))

        m = Net()
        fn, params = functionalize(m)
        ids_t = torch.randint(0, 32, (2, 6))
        with torch.no_grad():
            expected = m(ids_t).numpy()
        got = np.asarray(fn(params, jnp.asarray(ids_t.numpy())))
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)

    def test_conv_bn_pool(self):
        m = torch.nn.Sequential(
            torch.nn.Conv2d(3, 8, 3, padding=1),
            torch.nn.BatchNorm2d(8),
            torch.nn.ReLU(),
            torch.nn.MaxPool2d(2),
            torch.nn.Flatten(1),
            torch.nn.Linear(8 * 4 * 4, 10),
        ).eval()
        _compare(m, torch.randn(2, 3, 8, 8))

    def test_unmapped_op_clear_error(self):

        class Net(torch.nn.Module):

            def forward(self, x):
                return torch.fft.fft(x).real

        fn, params = functionalize(Net())
        with pytest.raises(NotImplementedError, match="no jax mapping"):
            fn(params, jnp.ones((4,)))


class TestTrainConverted:

    def test_train_torch_model_with_parallelize(self):
        """The converted function trains under @alpa_tpu.parallelize."""
        import optax

        m = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.Tanh(),
                                torch.nn.Linear(32, 1))
        fn, params = functionalize(m)
        set_mode("dist")
        x = jnp.asarray(np.random.RandomState(0).randn(64, 16),
                        jnp.float32)
        y = jnp.asarray(np.random.RandomState(1).randn(64, 1), jnp.float32)
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)

        @alpa_tpu.parallelize(method=alpa_tpu.DataParallel(),
                              batch_argnums=(2, 3),
                              donate_argnums=(0, 1))
        def step(params, opt_state, x, y):

            def loss_fn(p):
                out = fn(p, x)
                return ((out - y)**2).mean()

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(params)
            updates, opt_state2 = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state2, loss

        losses = []
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses


class TestOptimAndTrainer:

    def test_adam_matches_torch_adam(self):
        """Functional adam == torch.optim.Adam trajectories (the reference
        ships a placeholder here, ref alpa/torch/optim/adam.py:24)."""
        from alpa_tpu.torch_frontend.optim import adam

        m = torch.nn.Linear(4, 3)
        x = torch.randn(8, 4)
        y = torch.randn(8, 3)
        opt = torch.optim.Adam(m.parameters(), lr=1e-2)
        fn, params = functionalize(m)
        optim_func, _init, state = adam(lr=1e-2)(params)

        xj, yj = jnp.asarray(x.numpy()), jnp.asarray(y.numpy())
        for _ in range(5):
            # torch side
            opt.zero_grad()
            loss = ((m(x) - y)**2).mean()
            loss.backward()
            opt.step()
            # jax side
            grads = jax.grad(
                lambda p: ((fn(p, xj) - yj)**2).mean())(params)
            params, state = optim_func(params, state, grads)
        with torch.no_grad():
            want = m(x).numpy()
        got = np.asarray(fn(params, xj))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_trainer_loop(self):
        """TorchTrainer: torch module in, parallel train steps out
        (ref alpa/torch/trainer.py train_torch_module)."""
        from alpa_tpu.torch_frontend import TorchTrainer
        from alpa_tpu.torch_frontend.optim import sgd

        m = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.Tanh(),
                                torch.nn.Linear(32, 1))
        trainer = TorchTrainer(
            m, loss_func=lambda out, tgt: ((out - tgt)**2).mean(),
            optim_gen=sgd(lr=5e-2, momentum=0.9),
            method=alpa_tpu.DataParallel())
        x = torch.randn(64, 16)
        y = torch.randn(64, 1)
        losses = trainer.fit([(x, y)] * 10)
        assert losses[-1] < losses[0] * 0.8, losses


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
