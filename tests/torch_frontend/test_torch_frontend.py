"""Torch frontend: fx -> jax conversion parity with torch eager
(ref alpa/torch tests)."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax
import jax.numpy as jnp

import alpa_tpu
from alpa_tpu.torch_frontend import functionalize, set_mode


def _compare(module, *torch_inputs, rtol=1e-4):
    fn, params = functionalize(module)
    with torch.no_grad():
        expected = module(*torch_inputs).numpy()
    jax_inputs = [jnp.asarray(t.numpy()) for t in torch_inputs]
    got = np.asarray(fn(params, *jax_inputs))
    np.testing.assert_allclose(got, expected, rtol=rtol, atol=rtol)
    return fn, params, jax_inputs


class _MHAWrap(torch.nn.Module):
    """Self-attention through nn.MultiheadAttention as an fx leaf."""

    def __init__(self, mha):
        super().__init__()
        self.mha = mha

    def forward(self, x):
        out, _ = self.mha(x, x, x)
        return out


class TestConversion:

    def test_mlp(self):
        m = torch.nn.Sequential(
            torch.nn.Linear(16, 32), torch.nn.ReLU(),
            torch.nn.Linear(32, 8), torch.nn.Softmax(dim=-1))
        _compare(m, torch.randn(4, 16))

    def test_functional_ops(self):

        class Net(torch.nn.Module):

            def __init__(self):
                super().__init__()
                self.fc = torch.nn.Linear(8, 8)

            def forward(self, x):
                h = torch.nn.functional.gelu(self.fc(x))
                h = h.transpose(0, 1).contiguous()
                h = h.view(-1)
                return (h * 2 + 1).mean()

        _compare(Net(), torch.randn(3, 8))

    def test_embedding_layernorm(self):

        class Net(torch.nn.Module):

            def __init__(self):
                super().__init__()
                self.emb = torch.nn.Embedding(32, 16)
                self.ln = torch.nn.LayerNorm(16)
                self.head = torch.nn.Linear(16, 4)

            def forward(self, ids):
                return self.head(self.ln(self.emb(ids)))

        m = Net()
        fn, params = functionalize(m)
        ids_t = torch.randint(0, 32, (2, 6))
        with torch.no_grad():
            expected = m(ids_t).numpy()
        got = np.asarray(fn(params, jnp.asarray(ids_t.numpy())))
        np.testing.assert_allclose(got, expected, rtol=1e-4, atol=1e-4)

    def test_conv_bn_pool(self):
        m = torch.nn.Sequential(
            torch.nn.Conv2d(3, 8, 3, padding=1),
            torch.nn.BatchNorm2d(8),
            torch.nn.ReLU(),
            torch.nn.MaxPool2d(2),
            torch.nn.Flatten(1),
            torch.nn.Linear(8 * 4 * 4, 10),
        ).eval()
        _compare(m, torch.randn(2, 3, 8, 8))

    def test_avg_pools_and_group_norm(self):
        m = torch.nn.Sequential(
            torch.nn.Conv2d(3, 8, 3, padding=1),
            torch.nn.GroupNorm(4, 8),
            torch.nn.ReLU(),
            torch.nn.AvgPool2d(2),
            torch.nn.AdaptiveAvgPool2d((1, 1)),
            torch.nn.Flatten(1),
        ).eval()
        _compare(m, torch.randn(2, 3, 8, 8))

    def test_conv_transpose2d(self):
        for groups, opad in ((1, 0), (2, 1)):
            m = torch.nn.Sequential(
                torch.nn.ConvTranspose2d(4, 6, 3, stride=2, padding=1,
                                         output_padding=opad,
                                         groups=groups)).eval()
            _compare(m, torch.randn(2, 4, 5, 5))

    def test_batch_norm_1d(self):
        m = torch.nn.Sequential(torch.nn.Linear(8, 16),
                                torch.nn.BatchNorm1d(16)).eval()
        # populate non-trivial running stats
        with torch.no_grad():
            m[1].running_mean += torch.randn(16) * 0.1
            m[1].running_var += torch.rand(16)
        _compare(m, torch.randn(4, 8))

    def test_batch_norm_no_tracked_stats(self):
        """track_running_stats=False modules use batch statistics even in
        eval mode (torch semantics) and must not KeyError on the missing
        running_mean/var buffers."""
        m = torch.nn.Sequential(
            torch.nn.Linear(8, 16),
            torch.nn.BatchNorm1d(16, track_running_stats=False)).eval()
        _compare(m, torch.randn(4, 8))

    def test_multihead_attention(self):
        for batch_first in (True, False):
            m = torch.nn.MultiheadAttention(16, 4,
                                            batch_first=batch_first).eval()
            # trace through a wrapper module so fx sees a call_module node
            wrap = _MHAWrap(m).eval()
            fn, params = functionalize(wrap)
            x = torch.randn((2, 6, 16) if batch_first else (6, 2, 16))
            with torch.no_grad():
                expected = wrap(x).numpy()
            got = np.asarray(fn(params, jnp.asarray(x.numpy())))
            np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)

    def test_scaled_dot_product_attention(self):

        class Net(torch.nn.Module):

            def forward(self, q, k, v):
                return torch.nn.functional.scaled_dot_product_attention(
                    q, k, v, is_causal=True)

        q = torch.randn(2, 4, 8, 16)
        _compare(Net(), q, torch.randn(2, 4, 8, 16),
                 torch.randn(2, 4, 8, 16))

    def test_sdpa_causal_cross_length(self):
        """torch's is_causal is TOP-LEFT aligned when lq != lk (ADVICE r3)."""
        from alpa_tpu.torch_frontend.converter import \
            _scaled_dot_product_attention
        q = torch.randn(2, 4, 5, 16)
        k = torch.randn(2, 4, 9, 16)
        v = torch.randn(2, 4, 9, 16)
        with torch.no_grad():
            expected = torch.nn.functional.scaled_dot_product_attention(
                q, k, v, is_causal=True).numpy()
        got = np.asarray(_scaled_dot_product_attention(
            jnp.asarray(q.numpy()), jnp.asarray(k.numpy()),
            jnp.asarray(v.numpy()), is_causal=True))
        np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)

    def test_batch_norm_training_uses_batch_stats(self):
        """training=True normalizes with batch statistics and warns that
        running-stat updates are dropped (ADVICE r3)."""
        import warnings as _warnings
        from alpa_tpu.torch_frontend.converter import _batch_norm
        x = torch.randn(8, 6)
        rm, rv = torch.randn(6) * 0.1, torch.rand(6) + 0.5
        w, b = torch.randn(6), torch.randn(6)
        with torch.no_grad():
            expected = torch.nn.functional.batch_norm(
                x, rm.clone(), rv.clone(), w, b, training=True).numpy()
        with _warnings.catch_warnings(record=True) as rec:
            _warnings.simplefilter("always")
            got = np.asarray(_batch_norm(
                jnp.asarray(x.numpy()), jnp.asarray(rm.numpy()),
                jnp.asarray(rv.numpy()), jnp.asarray(w.numpy()),
                jnp.asarray(b.numpy()), training=True))
        assert any("training=True" in str(r.message) for r in rec)
        np.testing.assert_allclose(got, expected, rtol=2e-4, atol=2e-4)

    def test_unmapped_op_clear_error(self):

        class Net(torch.nn.Module):

            def forward(self, x):
                return torch.fft.fft(x).real

        fn, params = functionalize(Net())
        with pytest.raises(NotImplementedError, match="no jax mapping"):
            fn(params, jnp.ones((4,)))


class TestTrainConverted:

    def test_train_torch_model_with_parallelize(self):
        """The converted function trains under @alpa_tpu.parallelize."""
        import optax

        m = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.Tanh(),
                                torch.nn.Linear(32, 1))
        fn, params = functionalize(m)
        set_mode("dist")
        x = jnp.asarray(np.random.RandomState(0).randn(64, 16),
                        jnp.float32)
        y = jnp.asarray(np.random.RandomState(1).randn(64, 1), jnp.float32)
        tx = optax.adam(1e-2)
        opt_state = tx.init(params)

        @alpa_tpu.parallelize(method=alpa_tpu.DataParallel(),
                              batch_argnums=(2, 3),
                              donate_argnums=(0, 1))
        def step(params, opt_state, x, y):

            def loss_fn(p):
                out = fn(p, x)
                return ((out - y)**2).mean()

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(params)
            updates, opt_state2 = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state2, loss

        losses = []
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state, x, y)
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.8, losses


def _make_resnet18(num_classes=10):
    """Stock torchvision resnet18 structure, built directly in torch
    (torchvision isn't installed in this image; this is the same
    BasicBlock/ResNet layout, ref torchvision.models.resnet)."""

    class BasicBlock(torch.nn.Module):
        def __init__(self, cin, cout, stride=1):
            super().__init__()
            self.conv1 = torch.nn.Conv2d(cin, cout, 3, stride, 1,
                                         bias=False)
            self.bn1 = torch.nn.BatchNorm2d(cout)
            self.relu = torch.nn.ReLU(inplace=True)
            self.conv2 = torch.nn.Conv2d(cout, cout, 3, 1, 1, bias=False)
            self.bn2 = torch.nn.BatchNorm2d(cout)
            self.down = None
            if stride != 1 or cin != cout:
                self.down = torch.nn.Sequential(
                    torch.nn.Conv2d(cin, cout, 1, stride, bias=False),
                    torch.nn.BatchNorm2d(cout))

        def forward(self, x):
            identity = x if self.down is None else self.down(x)
            out = self.relu(self.bn1(self.conv1(x)))
            out = self.bn2(self.conv2(out))
            out += identity
            return self.relu(out)

    class ResNet18(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.conv1 = torch.nn.Conv2d(3, 64, 7, 2, 3, bias=False)
            self.bn1 = torch.nn.BatchNorm2d(64)
            self.relu = torch.nn.ReLU(inplace=True)
            self.maxpool = torch.nn.MaxPool2d(3, 2, 1)
            layers = []
            cin = 64
            for cout, stride in ((64, 1), (64, 1), (128, 2), (128, 1),
                                 (256, 2), (256, 1), (512, 2), (512, 1)):
                layers.append(BasicBlock(cin, cout, stride))
                cin = cout
            self.layers = torch.nn.Sequential(*layers)
            self.avgpool = torch.nn.AdaptiveAvgPool2d((1, 1))
            self.fc = torch.nn.Linear(512, num_classes)

        def forward(self, x):
            x = self.maxpool(self.relu(self.bn1(self.conv1(x))))
            x = self.layers(x)
            x = self.avgpool(x)
            x = torch.flatten(x, 1)
            return self.fc(x)

    return ResNet18()


class TestResNet18:

    def test_resnet18_converts_and_matches_eager(self):
        m = _make_resnet18().eval()
        _compare(m, torch.randn(2, 3, 32, 32), rtol=5e-3)

    @pytest.mark.slow
    def test_resnet18_trains_on_mesh(self):
        """Converted resnet18 trains end-to-end under @parallelize on the
        8-device mesh (VERDICT r2 next #9).  BatchNorm uses frozen
        running stats (eval-mode functionalization); conv/fc/affine
        weights train."""
        import optax

        m = _make_resnet18(num_classes=10)
        fn, params, buffers = functionalize(m, split_buffers=True)
        set_mode("dist")
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.randn(16, 3, 32, 32), jnp.float32)
        y = jnp.asarray(rs.randint(0, 10, (16,)), jnp.int32)
        tx = optax.adam(3e-3)
        opt_state = tx.init(params)

        @alpa_tpu.parallelize(method=alpa_tpu.DataParallel(),
                              batch_argnums=(2, 3),
                              donate_argnums=(0, 1))
        def step(params, opt_state, x, y):

            def loss_fn(p):
                logits = fn({**p, **buffers}, x)
                onehot = jax.nn.one_hot(y, 10)
                return -(jax.nn.log_softmax(logits) * onehot).sum(-1).mean()

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(params)
            updates, opt_state2 = tx.update(grads, opt_state)
            return optax.apply_updates(params, updates), opt_state2, loss

        losses = []
        for _ in range(15):
            params, opt_state, loss = step(params, opt_state, x, y)
            losses.append(float(loss))
        # 16 random samples, 10 classes: adam should be well on the way
        # to memorizing them
        assert losses[-1] < losses[0] * 0.7, losses


class TestOptimAndTrainer:

    def test_adam_matches_torch_adam(self):
        """Functional adam == torch.optim.Adam trajectories (the reference
        ships a placeholder here, ref alpa/torch/optim/adam.py:24)."""
        from alpa_tpu.torch_frontend.optim import adam

        m = torch.nn.Linear(4, 3)
        x = torch.randn(8, 4)
        y = torch.randn(8, 3)
        opt = torch.optim.Adam(m.parameters(), lr=1e-2)
        fn, params = functionalize(m)
        optim_func, _init, state = adam(lr=1e-2)(params)

        xj, yj = jnp.asarray(x.numpy()), jnp.asarray(y.numpy())
        for _ in range(5):
            # torch side
            opt.zero_grad()
            loss = ((m(x) - y)**2).mean()
            loss.backward()
            opt.step()
            # jax side
            grads = jax.grad(
                lambda p: ((fn(p, xj) - yj)**2).mean())(params)
            params, state = optim_func(params, state, grads)
        with torch.no_grad():
            want = m(x).numpy()
        got = np.asarray(fn(params, xj))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)

    def test_trainer_loop(self):
        """TorchTrainer: torch module in, parallel train steps out
        (ref alpa/torch/trainer.py train_torch_module)."""
        from alpa_tpu.torch_frontend import TorchTrainer
        from alpa_tpu.torch_frontend.optim import sgd

        m = torch.nn.Sequential(torch.nn.Linear(16, 32), torch.nn.Tanh(),
                                torch.nn.Linear(32, 1))
        trainer = TorchTrainer(
            m, loss_func=lambda out, tgt: ((out - tgt)**2).mean(),
            optim_gen=sgd(lr=5e-2, momentum=0.9),
            method=alpa_tpu.DataParallel())
        x = torch.randn(64, 16)
        y = torch.randn(64, 1)
        losses = trainer.fit([(x, y)] * 10)
        assert losses[-1] < losses[0] * 0.8, losses


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
