"""BatchNorm running-stat updates as explicit outputs
(``functionalize(mutable_buffers=True)``): torch's in-place side effect
becomes a returned updates dict, matching torch's multi-step trajectory
exactly — the last lossy train-mode semantic in the fx frontend.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax                                                     # noqa: E402
import jax.numpy as jnp                                        # noqa: E402

from alpa_tpu.torch_frontend import functionalize              # noqa: E402


def _net():
    torch.manual_seed(0)
    return torch.nn.Sequential(
        torch.nn.Conv2d(3, 8, 3, padding=1),
        torch.nn.BatchNorm2d(8),
        torch.nn.ReLU(),
        torch.nn.Conv2d(8, 4, 3, padding=1),
        torch.nn.BatchNorm2d(4))


class TestMutableBuffers:

    def test_three_step_running_stats_match_torch(self):
        tm = _net().train()
        fn, trainable, buffers = functionalize(
            _net().train(), split_buffers=True, mutable_buffers=True)

        rng = np.random.RandomState(0)
        for step in range(3):
            x = rng.randn(4, 3, 6, 6).astype(np.float32)
            want = tm(torch.tensor(x)).detach().numpy()
            got, updates = fn({**trainable, **buffers}, jnp.asarray(x))
            np.testing.assert_allclose(np.asarray(got), want,
                                       rtol=1e-4, atol=1e-4)
            buffers = {**buffers, **updates}

        for name, buf in tm.state_dict().items():
            if "running" in name or "num_batches" in name:
                np.testing.assert_allclose(
                    np.asarray(buffers[name]), buf.numpy(),
                    rtol=1e-4, atol=1e-5, err_msg=name)
        assert int(buffers["1.num_batches_tracked"]) == 3

    def test_eval_mode_emits_no_updates(self):
        m = _net().eval()
        fn, params = functionalize(m, mutable_buffers=True)
        x = np.random.RandomState(1).randn(2, 3, 6, 6).astype(np.float32)
        out, updates = fn(params, jnp.asarray(x))
        assert updates == {}
        want = m(torch.tensor(x)).detach().numpy()
        np.testing.assert_allclose(np.asarray(out), want, rtol=1e-4,
                                   atol=1e-4)

    def test_updates_work_under_jit(self):
        fn, trainable, buffers = functionalize(
            _net().train(), split_buffers=True, mutable_buffers=True)
        jf = jax.jit(fn)
        x = jnp.asarray(np.random.RandomState(2)
                        .randn(4, 3, 6, 6).astype(np.float32))
        out, updates = jf({**trainable, **buffers}, x)
        assert set(updates) == {
            "1.running_mean", "1.running_var", "1.num_batches_tracked",
            "4.running_mean", "4.running_var", "4.num_batches_tracked"}

    def test_momentum_none_rejected(self):
        m = torch.nn.Sequential(
            torch.nn.BatchNorm1d(4, momentum=None)).train()
        with pytest.raises(NotImplementedError, match="momentum"):
            functionalize(m, mutable_buffers=True)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
