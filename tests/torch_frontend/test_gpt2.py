"""HF GPT-2 (torch) through the fx frontend, end to end (VERDICT r4
next #9): transformers' GPT2Block converts as a leaf module (the
explicit mapping in converter._convert_gpt2_block), the wrapper drives
the genuine HF submodules, logits match transformers exactly, and a
parallelized train step on the 8-device CPU mesh matches torch
autograd + SGD numerics.

Also covers the explicit dropout policy: train-mode dropout refuses to
convert without a choice; 'identity' is deterministic; 'rng' applies
real per-site dropout.
"""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax                                                     # noqa: E402
import jax.numpy as jnp                                        # noqa: E402

import alpa_tpu                                                # noqa: E402
from alpa_tpu.torch_frontend import functionalize              # noqa: E402


def _tiny_gpt2():
    from transformers import GPT2Config, GPT2LMHeadModel
    cfg = transformers.GPT2Config(
        n_layer=2, n_embd=64, n_head=4, vocab_size=128, n_positions=64,
        attn_pdrop=0.0, resid_pdrop=0.0, embd_pdrop=0.0,
        attn_implementation="eager")
    torch.manual_seed(0)
    return GPT2LMHeadModel(cfg).eval()


class GPT2Wrapper(torch.nn.Module):
    """Drives the genuine HF GPT-2 submodules with an explicit additive
    causal mask (transformers' own create_causal_mask path resists fx
    tracing; the blocks themselves convert as leaves)."""

    def __init__(self, m):
        super().__init__()
        t = m.transformer
        self.wte, self.wpe, self.h, self.ln_f = t.wte, t.wpe, t.h, t.ln_f
        self.lm_head = m.lm_head

    def forward(self, input_ids, causal_mask):
        pos = torch.arange(input_ids.size(1), device=input_ids.device)
        x = self.wte(input_ids) + self.wpe(pos)
        for block in self.h:
            x = block(x, attention_mask=causal_mask)[0]
        return self.lm_head(self.ln_f(x))


def _causal_mask(s):
    return np.where(np.tril(np.ones((s, s), bool)), 0.0,
                    np.float32(np.finfo(np.float32).min))[None, None] \
        .astype(np.float32)


def _functionalized(model):
    from transformers.models.gpt2.modeling_gpt2 import GPT2Block
    return functionalize(GPT2Wrapper(model).eval(),
                         leaf_modules=(GPT2Block,))


class TestGPT2Forward:

    def test_logits_match_transformers(self):
        model = _tiny_gpt2()
        rng = np.random.RandomState(0)
        ids = rng.randint(0, 128, (2, 12))
        want = model(torch.tensor(ids)).logits.detach().numpy()

        fn, params = _functionalized(model)
        got = np.asarray(fn(params, jnp.asarray(ids),
                            jnp.asarray(_causal_mask(12))))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


class TestGPT2Train:

    def test_parallelized_sgd_step_matches_torch(self):
        """One CE-loss SGD step, parallelized on the 8-device CPU mesh,
        lands on the same parameters torch autograd computes."""
        model = _tiny_gpt2()
        rng = np.random.RandomState(1)
        ids = rng.randint(0, 128, (8, 12))
        labels = rng.randint(0, 128, (8, 12))
        lr = 0.05

        # ---- torch side ----
        tm = _tiny_gpt2()
        tm.train()  # grads; dropout probs are 0 in this config
        logits = GPT2Wrapper(tm)(torch.tensor(ids),
                                 torch.tensor(_causal_mask(12)))
        loss_t = torch.nn.functional.cross_entropy(
            logits.reshape(-1, 128), torch.tensor(labels).reshape(-1))
        loss_t.backward()
        with torch.no_grad():
            torch_after = {
                k: (v - lr * v.grad).detach().numpy()
                for k, v in tm.named_parameters() if v.grad is not None
            }

        # ---- alpa_tpu side ----
        fn, params = _functionalized(model)
        mask = jnp.asarray(_causal_mask(12))

        def train_step(params, batch):

            def loss_fn(p):
                lg = fn(p, batch["ids"], mask)
                ll = jax.nn.log_softmax(lg.reshape(-1, 128))
                return -jnp.mean(
                    jnp.take_along_axis(
                        ll, batch["labels"].reshape(-1, 1), axis=1))

            loss, grads = alpa_tpu.value_and_grad(loss_fn)(params)
            new = jax.tree_util.tree_map(lambda w, g: w - lr * g,
                                         params, grads)
            return new, loss

        alpa_tpu.init(cluster="local")
        pstep = alpa_tpu.parallelize(
            train_step, method=alpa_tpu.DataParallel(), batch_argnums=(1,))
        batch = {"ids": jnp.asarray(ids), "labels": jnp.asarray(labels)}
        new_params, loss_j = pstep(params, batch)

        np.testing.assert_allclose(float(loss_j), float(loss_t),
                                   rtol=1e-4, atol=1e-4)
        # wte/lm_head are tied in HF GPT-2: torch accumulates ONE grad
        # for the shared tensor while the jax params dict carries two
        # separately-updated entries (their grads sum to torch's — see
        # test_tied_embedding_gradients), so compare every non-tied
        # parameter.  torch names carry a "transformer." prefix the
        # wrapper's state_dict does not.
        checked = 0
        for k, want in torch_after.items():
            k2 = k[len("transformer."):] if \
                k.startswith("transformer.") else k
            if k2 not in new_params or k2.startswith("wte") or \
                    k2 == "lm_head.weight":
                continue
            np.testing.assert_allclose(np.asarray(new_params[k2]), want,
                                       rtol=2e-3, atol=2e-3, err_msg=k2)
            checked += 1
        assert checked >= 10  # ln/attn/mlp params across both blocks

    def test_tied_embedding_gradients(self):
        """HF GPT-2 ties wte and lm_head; the functionalized params hold
        two entries backed by the same torch tensor.  The jax grads of
        the two must SUM to torch's tied grad."""
        model = _tiny_gpt2()
        tm = _tiny_gpt2()
        tm.train()
        rng = np.random.RandomState(2)
        ids = rng.randint(0, 128, (4, 8))
        labels = rng.randint(0, 128, (4, 8))
        logits = GPT2Wrapper(tm)(torch.tensor(ids),
                                 torch.tensor(_causal_mask(8)))
        loss_t = torch.nn.functional.cross_entropy(
            logits.reshape(-1, 128), torch.tensor(labels).reshape(-1))
        loss_t.backward()
        tied_grad = tm.transformer.wte.weight.grad.numpy()

        fn, params = _functionalized(model)
        mask = jnp.asarray(_causal_mask(8))

        def loss_fn(p):
            lg = fn(p, jnp.asarray(ids), mask)
            ll = jax.nn.log_softmax(lg.reshape(-1, 128))
            return -jnp.mean(jnp.take_along_axis(
                ll, jnp.asarray(labels).reshape(-1, 1), axis=1))

        grads = jax.grad(loss_fn)(params)
        got = np.asarray(grads["wte.weight"]) + \
            np.asarray(grads["lm_head.weight"])
        np.testing.assert_allclose(got, tied_grad, rtol=2e-3, atol=2e-3)


class TestDropoutPolicy:

    def _mlp(self, p=0.5):
        torch.manual_seed(0)
        return torch.nn.Sequential(
            torch.nn.Linear(8, 16), torch.nn.ReLU(),
            torch.nn.Dropout(p), torch.nn.Linear(16, 4))

    def test_train_mode_dropout_requires_choice(self):
        m = self._mlp().train()
        with pytest.raises(ValueError, match="explicit policy"):
            functionalize(m)

    def test_identity_policy_is_deterministic(self):
        m = self._mlp().train()
        with pytest.warns(UserWarning):
            fn, params = functionalize(m, dropout="identity")
        x = jnp.ones((2, 8))
        a, b = fn(params, x), fn(params, x)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # identity == the eval-mode module's output
        want = self._mlp().eval()(torch.ones(2, 8)).detach().numpy()
        np.testing.assert_allclose(np.asarray(a), want, rtol=1e-5,
                                   atol=1e-5)

    def test_rng_policy_applies_real_dropout(self):
        m = self._mlp(p=0.4).train()
        with pytest.warns(UserWarning):
            fn, params = functionalize(m, dropout="rng")
        x = jnp.ones((4, 8))
        with pytest.raises(ValueError, match="rng"):
            fn(params, x)
        a = np.asarray(fn(params, x, rng=jax.random.PRNGKey(0)))
        b = np.asarray(fn(params, x, rng=jax.random.PRNGKey(1)))
        c = np.asarray(fn(params, x, rng=jax.random.PRNGKey(0)))
        assert not np.array_equal(a, b)          # random across keys
        np.testing.assert_array_equal(a, c)      # deterministic per key
        # unbiased in expectation: mean over many keys ~ eval output
        outs = [np.asarray(fn(params, x, rng=jax.random.PRNGKey(s)))
                for s in range(300)]
        fn2, p2 = functionalize(self._mlp(p=0.4).eval())
        det = np.asarray(fn2(p2, x))
        np.testing.assert_allclose(np.mean(outs, axis=0), det,
                                   rtol=0.25, atol=0.25)

    def test_eval_mode_needs_no_choice(self):
        m = self._mlp().eval()
        fn, params = functionalize(m)
        want = m(torch.ones(2, 8)).detach().numpy()
        np.testing.assert_allclose(
            np.asarray(fn(params, jnp.ones((2, 8)))), want,
            rtol=1e-5, atol=1e-5)


class TestLeafDropoutRefusal:
    """The leaf-module escape hatch must not evade the explicit dropout
    policy: GPT2Block converts as a LEAF (the tracer never sees its
    nn.Dropout children, so _find_active_dropout cannot), and the leaf
    mapping is deterministic — converting a train-mode block with live
    dropout would silently mistrain.  Regression for that gap."""

    def _gpt2(self, attn_pdrop, resid_pdrop, train):
        cfg = transformers.GPT2Config(
            n_layer=1, n_embd=32, n_head=2, vocab_size=64,
            n_positions=32, attn_pdrop=attn_pdrop,
            resid_pdrop=resid_pdrop, embd_pdrop=0.0,
            attn_implementation="eager")
        torch.manual_seed(0)
        m = transformers.GPT2LMHeadModel(cfg)
        return m.train() if train else m.eval()

    def _convert(self, model):
        from transformers.models.gpt2.modeling_gpt2 import GPT2Block
        wrapper = GPT2Wrapper(model)
        wrapper.train(model.training)
        return functionalize(wrapper, leaf_modules=(GPT2Block,),
                             dropout="identity")

    def test_train_mode_block_with_pdrop_refuses(self):
        model = self._gpt2(attn_pdrop=0.1, resid_pdrop=0.1, train=True)
        with pytest.raises(ValueError, match="active dropout"):
            self._convert(model)

    def test_train_mode_resid_dropout_alone_refuses(self):
        model = self._gpt2(attn_pdrop=0.0, resid_pdrop=0.1, train=True)
        with pytest.raises(ValueError, match="resid_dropout"):
            self._convert(model)

    def test_zero_pdrop_train_block_converts(self):
        model = self._gpt2(attn_pdrop=0.0, resid_pdrop=0.0, train=True)
        fn, params = self._convert(model)
        ids = np.arange(4, dtype=np.int64)[None]
        out = fn(params, jnp.asarray(ids), jnp.asarray(_causal_mask(4)))
        assert np.isfinite(np.asarray(out)).all()

    def test_eval_block_with_pdrop_converts_and_matches(self):
        model = self._gpt2(attn_pdrop=0.1, resid_pdrop=0.1, train=False)
        fn, params = self._convert(model)
        ids = np.arange(4, dtype=np.int64)[None]
        want = model(torch.tensor(ids)).logits.detach().numpy()
        got = np.asarray(fn(params, jnp.asarray(ids),
                            jnp.asarray(_causal_mask(4))))
        np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
