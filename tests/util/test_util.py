"""Utility unit tests (ref tests/util/: OrderedSet, cost model, flops)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from alpa_tpu.device_mesh import LogicalDeviceMesh
from alpa_tpu.util import (OrderedSet, compute_gpt_parameter_count,
                           compute_gpt_tflops, count_communication_primitives,
                           divide_evenly, jaxpr_eqn_flops, split_list)


class TestOrderedSet:

    def test_order_preserved(self):
        s = OrderedSet([3, 1, 2])
        s.add(1)
        s.add(5)
        assert list(s) == [3, 1, 2, 5]

    def test_set_ops(self):
        a = OrderedSet([1, 2, 3])
        b = OrderedSet([2, 3, 4])
        assert list(a | b) == [1, 2, 3, 4]
        assert list(a & b) == [2, 3]
        assert list(a - b) == [1]
        assert a == {1, 2, 3}
        a.discard(99)  # no error
        a.remove(1)
        assert 1 not in a

    def test_pop_fifo(self):
        s = OrderedSet([7, 8, 9])
        assert s.pop() == 7
        assert len(s) == 2


class TestCostModel:

    def test_collective_costs_scale_with_axis(self):
        lm = LogicalDeviceMesh(None, np.arange(8).reshape(4, 2),
                               mesh_beta=(0.1, 0.01))
        # bigger axis, bigger beta -> bigger cost
        assert lm.all_reduce_cost(1 << 20, 0) > lm.all_reduce_cost(
            1 << 20, 1)
        # single-element axis is free
        lm2 = LogicalDeviceMesh(None, np.arange(4).reshape(4, 1))
        assert lm2.all_gather_cost(1 << 20, 1) == 0.0
        # all-reduce ~ 2x all-gather bytes on a ring
        ar = lm.all_reduce_cost(1 << 24, 0)
        ag = lm.all_gather_cost(1 << 24, 0)
        assert 1.5 < ar / ag < 2.5

    def test_gpt_flops_accounting(self):
        n = compute_gpt_parameter_count(12, 768, 51200)
        assert 1.2e8 < n < 1.7e8  # ~GPT-125M
        tf = compute_gpt_tflops(8, 1024, 12, 768, 51200, 1, latency=0.1)
        assert tf > 0

    def test_eqn_flops_dot(self):
        cj = jax.make_jaxpr(lambda a, b: a @ b)(
            jnp.ones((64, 128)), jnp.ones((128, 32)))
        dot = [e for e in cj.jaxpr.eqns
               if e.primitive.name == "dot_general"][0]
        assert jaxpr_eqn_flops(dot) == 2 * 64 * 128 * 32


class TestHloCounting:

    def test_opcode_position_only(self):
        hlo = """
%ar = f32[8]{0} all-reduce(f32[8]{0} %p0), replica_groups={}
%use = f32[8]{0} add(f32[8]{0} %ar, f32[8]{0} %p0)
%ag.1 = (f32[4]{0}, f32[4]{0}) all-gather-start(f32[2]{0} %x)
%d = f32[4]{0} all-gather-done((f32[4]{0}, f32[4]{0}) %ag.1)
"""
        total, ar, ag, rs, a2a = count_communication_primitives(hlo)
        assert (total, ar, ag, rs, a2a) == (2, 1, 1, 0, 0)


class TestListHelpers:

    def test_split_and_divide(self):
        assert split_list([1, 2, 3, 4, 5], [2, 3]) == [[1, 2], [3, 4, 5]]
        assert divide_evenly(10, 3) == [4, 3, 3]


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
