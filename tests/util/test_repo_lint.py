"""Repo-invariant lint gate (ISSUE 8 satellite): the AST lint must be
clean on every commit.  See alpa_tpu/analysis/lint.py for the rule set
and docs/static_analysis.md for the rationale; run standalone with
``python scripts/verify_tool.py verify lint``."""
from alpa_tpu.analysis import lint


def test_repo_lint_is_clean():
    violations = lint.run_lint()
    assert not violations, "\n" + lint.format_report(violations)


def test_lint_rules_actually_detect(tmp_path):
    """The gate must not pass vacuously: seed a scratch repo with one
    violation of each class and check every rule fires."""
    pkg = tmp_path / "alpa_tpu"
    pkg.mkdir()
    (tmp_path / "docs").mkdir()
    (pkg / "global_env.py").write_text(
        "import os\n"
        "class GlobalConfig:\n"
        "    def __init__(self):\n"
        "        self.undocumented_knob = True\n")
    (pkg / "bad.py").write_text(
        "from alpa_tpu.timer import tracer\n"
        "REG.counter('bad_metric_name', 'description')\n"
        "REG.gauge('alpa_scratch_gauge', 'well-named but undocumented')\n"
        "fault.fire('no_such_site')\n"
        "call_with_retry(f, site='also_missing')\n")
    (pkg / "badcodec.py").write_text(
        "def encode(x, mode):\n"
        "    return x\n"
        "\n"
        "def decode(q, s, shape, dtype, mode):\n"
        "    return q\n")
    (pkg / "analysis").mkdir()
    (pkg / "analysis" / "badfinding.py").write_text(
        "CODE = 'equiv.scratch-undocumented'\n")
    codes = {v.code for v in lint.run_lint(root=str(tmp_path))}
    assert codes >= {"config-env", "config-doc", "metric-name",
                     "metric-doc", "timer-import", "fault-site",
                     "codec-bound", "finding-code-doc"}, codes


def test_known_sites_registry_matches_docstring_table():
    """Every registered fault site must be documented in the fault.py
    docstring table (and the registry must cover the instrumented
    set the rest of the stack fires)."""
    import alpa_tpu.fault as fault
    for site in fault.KNOWN_SITES:
        assert f"``{site}``" in fault.__doc__, (
            f"site {site!r} missing from the fault.py docstring table")
    assert {"probe", "stage_launch", "cross_mesh_send",
            "cross_mesh_recv", "distributed_init"} <= fault.KNOWN_SITES
