"""Single-program SPMD pipeline (shard_map + ppermute) tests."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from alpa_tpu.parallel.spmd_pipeline import (spmd_pipeline,
                                             spmd_pipeline_1f1b,
                                             stack_pytrees)
from alpa_tpu.testing import skip_if_old_jax


def _mesh(shape, names):
    n = int(np.prod(shape))
    return Mesh(np.array(jax.devices()[:n]).reshape(shape), names)


class TestSpmdPipeline:

    @skip_if_old_jax("partial-automatic shard_map miscompiles (XLA "
                     "PartitionId aborts), so jax_compat refuses it with "
                     "NotImplementedError")
    def test_forward_matches_serial(self):
        mesh = _mesh((2, 4), ("dp", "pp"))
        S = 4
        Ws = [
            jax.random.normal(jax.random.PRNGKey(i), (16, 16)) * 0.3
            for i in range(S)
        ]
        stacked = jax.device_put(jnp.stack(Ws), NamedSharding(mesh, P("pp")))
        x = jax.random.normal(jax.random.PRNGKey(9), (8, 16))

        def stage_fn(W, x, _):
            return jnp.tanh(x @ W)

        def pipelined(stacked, x):
            mbs = x.reshape(4, 2, 16)
            y = spmd_pipeline(stage_fn, stacked, mbs, mesh=mesh)
            return y.reshape(8, 16)

        with jax.set_mesh(mesh):
            out = jax.jit(pipelined)(stacked, x)
        h = x
        for W in Ws:
            h = jnp.tanh(h @ W)
        np.testing.assert_allclose(np.asarray(out), np.asarray(h),
                                   rtol=1e-5, atol=1e-6)

    def test_gradients_match_serial(self):
        mesh = _mesh((8,), ("pp",))
        S = 8
        Ws = [
            jax.random.normal(jax.random.PRNGKey(i), (8, 8)) * 0.3
            for i in range(S)
        ]
        stacked_host = jnp.stack(Ws)
        stacked = jax.device_put(stacked_host, NamedSharding(mesh, P("pp")))
        x = jax.random.normal(jax.random.PRNGKey(9), (4, 8))

        def stage_fn(W, x, _):
            return jnp.tanh(x @ W)

        def loss_p(stacked, x):
            mbs = x.reshape(2, 2, 8)
            y = spmd_pipeline(stage_fn, stacked, mbs, mesh=mesh)
            return (y**2).mean()

        def loss_s(stacked, x):
            h = x
            for s in range(S):
                h = jnp.tanh(h @ stacked[s])
            return (h**2).mean()

        with jax.set_mesh(mesh):
            gp = jax.jit(jax.grad(loss_p))(stacked, x)
        gs = jax.grad(loss_s)(stacked_host, x)
        np.testing.assert_allclose(np.asarray(gp), np.asarray(gs),
                                   rtol=1e-4, atol=1e-6)


class TestSpmdPipeline1F1B:
    """Single-program 1F1B: loss + grads + input cotangents from one
    interleaved scan must match serial autodiff (VERDICT r1 next#8)."""

    def _setup(self, S, M, dim=8, mb=2):
        mesh = _mesh((S,), ("pp",))
        Ws = [
            jax.random.normal(jax.random.PRNGKey(i), (dim, dim)) * 0.3
            for i in range(S)
        ]
        stacked_host = jnp.stack(Ws)
        stacked = jax.device_put(stacked_host,
                                 NamedSharding(mesh, P("pp")))
        x = jax.random.normal(jax.random.PRNGKey(9), (M * mb, dim))
        labels = jax.random.normal(jax.random.PRNGKey(7), (M * mb, dim))
        return mesh, stacked_host, stacked, x, labels

    @staticmethod
    def _stage_fn(W, x, _):
        return jnp.tanh(x @ W)

    @staticmethod
    def _loss_fn(y, lbl):
        return jnp.mean((y - lbl) ** 2)

    @pytest.mark.parametrize("S,M", [(4, 4), (4, 8), (8, 8)])
    def test_matches_serial(self, S, M):
        mesh, stacked_host, stacked, x, labels = self._setup(S, M)
        mb = x.shape[0] // M

        def run(stacked, x, labels):
            mbs = x.reshape(M, mb, -1)
            lbls = labels.reshape(M, mb, -1)
            return spmd_pipeline_1f1b(self._stage_fn, self._loss_fn,
                                      stacked, mbs, lbls, mesh=mesh)

        with jax.set_mesh(mesh):
            loss, wgrad, dx = jax.jit(run)(stacked, x, labels)

        def loss_s(stacked, x):
            h = x
            for s in range(S):
                h = jnp.tanh(h @ stacked[s])
            # mean over microbatches of per-microbatch means == global
            # mean when microbatches are equal sized
            return jnp.mean((h - labels) ** 2)

        ls = loss_s(stacked_host, x)
        gs, dxs = jax.grad(loss_s, argnums=(0, 1))(stacked_host, x)
        np.testing.assert_allclose(float(loss), float(ls), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(wgrad), np.asarray(gs),
                                   rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(
            np.asarray(dx).reshape(x.shape), np.asarray(dxs),
            rtol=1e-4, atol=1e-6)

    def test_collectives_present(self):
        """Both directions of the pipeline ride ppermute (fwd
        activations + bwd cotangents), not all-gathers."""
        S, M = 4, 4
        mesh, _, stacked, x, labels = self._setup(S, M)
        mb = x.shape[0] // M

        def run(stacked, x, labels):
            mbs = x.reshape(M, mb, -1)
            lbls = labels.reshape(M, mb, -1)
            return spmd_pipeline_1f1b(self._stage_fn, self._loss_fn,
                                      stacked, mbs, lbls, mesh=mesh)

        with jax.set_mesh(mesh):
            hlo = (jax.jit(run).lower(stacked, x, labels).compile()
                   .as_text())
        assert "collective-permute" in hlo


class TestGraftEntry:

    @skip_if_old_jax("partial-automatic shard_map miscompiles (XLA "
                     "PartitionId aborts), so jax_compat refuses it with "
                     "NotImplementedError")
    def test_dryrun_multichip(self):
        import importlib.util
        import os
        spec = importlib.util.spec_from_file_location(
            "graft_entry",
            os.path.join(os.path.dirname(__file__), "..", "..",
                         "__graft_entry__.py"))
        ge = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(ge)
        ge.dryrun_multichip(8)


if __name__ == "__main__":
    pytest.main([__file__, "-x", "-q"])
