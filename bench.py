"""Benchmark driver: GPT train-step throughput on the available chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline anchor (BASELINE.md): the reference's published manual-3D GPT-2.6B
result of 37.01 TFLOPS/GPU on 8x V100 (ref benchmark/alpa/README.md:89-101).
vs_baseline = achieved TFLOPS-per-chip / 37.01.

Chip-protection discipline (the remote-attached chip's relay wedges on
near-OOM programs and stays wedged for a long time):

1. **HBM hard gate** — every on-chip config is estimated (params + optimizer
   state + activations) and refused outright above ``HBM_GATE_GB``.  The
   refusal is an error line, not an attempt.
2. **Probe-and-wait recovery** — before running the benchmark the parent
   probes the chip with a tiny matmul in a child process.  If the relay is
   wedged, it keeps probing every ``PROBE_INTERVAL_S`` until the self-budget
   is nearly spent (wedges clear on their own), then runs the benchmark the
   moment a probe succeeds.
3. **Child-process isolation** — the benchmark itself runs in a child with a
   hard timeout, so a wedge mid-run cannot hang the caller.
"""
import json
import os
import subprocess
import sys
import time

BASELINE_TFLOPS_PER_DEVICE = 37.01

# Relay ceiling, in *estimator* units.  estimate_hbm_gb is deliberately
# conservative (it counts fp32 logits + their grad without assuming XLA
# fuses or frees them): the known-good h2048-l16-bs8 config estimates
# 15.6 GB and runs at 76 TFLOPS; every config that wedged the relay
# (remat_policy="dots", batch 16, h2048-l24 with fp32 adam) estimates
# >= 20.2 GB.  The gate sits between with margin on the safe side.
HBM_GATE_GB = 16.0

PROBE_INTERVAL_S = 60.0
PROBE_TIMEOUT_S = 90.0
BENCH_TIMEOUT_S = 480.0
# Don't launch the heavy benchmark with less budget than compile + warmup
# + 10 timed iters realistically need — a mid-run kill on a just-recovered
# chip is itself a wedge risk.
MIN_ATTEMPT_S = 240.0
MAX_CHILD_FAILURES = 3

_PROBE_SRC = (
    "import jax, jax.numpy as jnp;"
    "x = jnp.ones((256, 256), jnp.bfloat16);"
    "print(float((x @ x)[0, 0]))"
)


def gpt_param_count(hidden_size, num_layers, vocab_size, seq_len,
                    mlp_ratio=4, tie_embeddings=True):
    per_layer = (4 + 2 * mlp_ratio) * hidden_size ** 2 \
        + (9 + 2 * mlp_ratio) * hidden_size  # biases + 2 LN
    emb = vocab_size * hidden_size + seq_len * hidden_size
    head = 0 if tie_embeddings else vocab_size * hidden_size
    return per_layer * num_layers + emb + head + 2 * hidden_size


def estimate_hbm_gb(config, batch_size, optimizer_bytes_per_param=8.0,
                    chunked_ce=False):
    """Estimated peak HBM for one train step of ``config`` at ``batch_size``.

    params are fp32 (flax param_dtype default) = 4 B/p; optimizer state
    defaults to fp32 adam (2 moments) = 8 B/p.  Activations assume
    per-block remat: L boundary activations + one live block's
    intermediates, in the compute dtype, plus fp32 logits (+ their grad)
    unless the loss is chunked.
    """
    import numpy as np
    p = gpt_param_count(config.hidden_size, config.num_layers,
                        config.vocab_size, config.seq_len, config.mlp_ratio,
                        config.tie_embeddings)
    act_bytes = np.dtype(config.dtype).itemsize
    tokens = batch_size * config.seq_len
    h = config.hidden_size
    # live block intermediates: qkv(3h) + attn scores/probs + proj(h) +
    # mlp(4h + 4h) + residuals — call it ~20h per token (bs8/s1024
    # attention scores are 32 MB/head-batch slice, negligible after fusing)
    per_block = tokens * 20 * h * act_bytes
    if getattr(config, "remat_blocks", False):
        # per-block remat: keep only block boundaries + one live block
        boundary = tokens * h * act_bytes * config.num_layers
        block_peak = per_block
        if getattr(config, "remat_policy", None) == "dots":
            # saved dot outputs per layer: qkv 3h + proj h + mlp 5h ≈ 9h
            boundary += tokens * 9 * h * act_bytes * config.num_layers
    else:
        # no remat: every layer's intermediates live until backward
        boundary = per_block * config.num_layers
        block_peak = 0
    logits = 0 if chunked_ce else 2 * tokens * config.vocab_size * 4
    total = p * (4.0 + optimizer_bytes_per_param) + boundary + block_peak \
        + logits + tokens * h * 4  # grads materialize alongside fp32 master
    return total / 1e9


def _probe_once():
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_SRC],
                           timeout=PROBE_TIMEOUT_S, capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _run_inner(timeout):
    """Run the benchmark child.

    Returns ``(json_line_or_None, error_or_None)`` where ``error`` is
    "timeout" or "rc=N: <stderr tail>" when no JSON line was produced.
    """
    cmd = [sys.executable, os.path.abspath(__file__), "--inner"]
    try:
        r = subprocess.run(cmd, timeout=timeout, capture_output=True,
                           text=True)
    except subprocess.TimeoutExpired:
        return None, "timeout"
    for line in (r.stdout or "").splitlines():
        if line.startswith("{"):
            return line, None
    return None, f"rc={r.returncode}: {(r.stderr or '')[-800:]}"


def _cpu_dispatch_us():
    """Per-instruction driver dispatch latency (us) measured on an
    8-device CPU mesh in a subprocess, or None if the measurement fails.
    Run when the TPU is wedged: dispatch is a pure-driver cost, so the
    CPU number is still meaningful (see benchmark/bench_dispatch.py)."""
    code = (
        "from alpa_tpu.platform import pin_cpu_platform;"
        "pin_cpu_platform(8);"
        "from scripts.dispatch_overhead_bench import measure;"
        "import json;"
        "print(json.dumps(measure(n_steps=3, dispatch_mode='registers')))")
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], timeout=600, capture_output=True,
            text=True, cwd=os.path.dirname(os.path.abspath(__file__)),
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        for line in (r.stdout or "").splitlines():
            if line.startswith("{"):
                return round(json.loads(line)["per_inst_us"], 2)
    except Exception:  # pylint: disable=broad-except
        pass
    return None


def _run_with_recovery(total_budget):
    t0 = time.time()
    probes = []
    child_errors = []
    while True:
        remaining = total_budget - (time.time() - t0)
        if remaining < PROBE_TIMEOUT_S + 30:
            break
        if len(child_errors) >= MAX_CHILD_FAILURES and \
                child_errors[-1] != "timeout":
            break  # deterministic child failure — retrying won't help
        ok = _probe_once()
        probes.append(ok)
        remaining = total_budget - (time.time() - t0)
        if ok:
            if remaining < MIN_ATTEMPT_S:
                break  # not enough budget for a safe full attempt
            line, err = _run_inner(min(BENCH_TIMEOUT_S, remaining - 10))
            if line is not None:
                print(line)
                # a gate refusal or measured failure carries detail.error;
                # exit nonzero so the harness can tell it from a real score
                try:
                    rec = json.loads(line)
                    return 1 if rec.get("detail", {}).get("error") else 0
                except ValueError:
                    return 0
            child_errors.append(err)
            sys.stderr.write(f"bench child failed ({err[:200]})\n")
            if err != "timeout":
                time.sleep(10)  # brief backoff before diagnosis retry
        else:
            time.sleep(min(PROBE_INTERVAL_S,
                           max(0.0, total_budget - (time.time() - t0))))
    print(json.dumps({
        "metric": "gpt_train_tflops_per_chip", "value": 0.0,
        "unit": "TFLOPS/chip", "vs_baseline": 0.0,
        "detail": {
            "error": ("bench child kept failing"
                      if child_errors and child_errors[-1] != "timeout"
                      else "device unresponsive for the whole bench window"),
            # the TPU is wedged but the driver isn't: report the CPU-mesh
            # register-dispatch latency so the run still carries a
            # dispatch-path datapoint (ISSUE 2)
            "cpu_dispatch_us": _cpu_dispatch_us(),
            "probe_history": ["ok" if p else "wedged" for p in probes],
            "child_errors": child_errors[-3:],
            "last_good_onchip": "76.06 TFLOPS/chip (vs_baseline 2.055, "
                                "mfu 0.386 of v5e peak)",
            "wedge_watch": "scripts/chip_watch.sh probes every 10 min "
                           "and auto-runs the recovery runbook "
                           "(benchmark/results/chip_watch.log is the "
                           "probe history)",
        },
    }))
    return 1


BENCH_SHAPES = {"": (2048, 16), "h2048l24": (2048, 24),
                "h2560l16": (2560, 16)}


def read_bench_variants():
    """(opt, ce, shape, errors): the env-selected experiment variants.
    Checked in BOTH the parent (instantly, before any probing burns the
    bench window) and the --inner child."""
    opt = os.environ.get("ALPA_TPU_BENCH_OPT", "adam")
    ce = os.environ.get("ALPA_TPU_BENCH_CE", "dense")
    shape = os.environ.get("ALPA_TPU_BENCH_SHAPE", "")
    errors = [f"{k}={v!r}" for k, v, ok in (
        ("ALPA_TPU_BENCH_OPT", opt, ("adam", "bf16adam")),
        ("ALPA_TPU_BENCH_CE", ce, ("dense", "chunked")),
        ("ALPA_TPU_BENCH_SHAPE", shape, tuple(BENCH_SHAPES)),
    ) if v not in ok]
    return opt, ce, shape, errors


def _refuse_variants(errors) -> int:
    print(json.dumps({
        "metric": "gpt_train_tflops_per_chip", "value": 0.0,
        "unit": "TFLOPS/chip", "vs_baseline": 0.0,
        "detail": {"error": f"unknown bench variant(s): {errors}"}}))
    return 1


def main():
    import jax

    # The axon sitecustomize force-registers the TPU relay platform and
    # overrides the JAX_PLATFORMS env var; only the config-level pin
    # actually keeps a CPU run off the relay (a wedged relay otherwise
    # hangs even `jax.devices()` under JAX_PLATFORMS=cpu).
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import jax.numpy as jnp
    import optax

    import alpa_tpu
    from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
    from alpa_tpu.model.model_util import gpt_lm_loss
    from alpa_tpu.util import compute_gpt_tflops

    devices = jax.devices()
    on_tpu = devices[0].platform in ("tpu", "axon")
    n_dev = len(devices)

    # Experiment variants, opt-in via env (the DEFAULT stays the known-
    # good config — never risk the official number on an experiment):
    #   ALPA_TPU_BENCH_OPT=bf16adam   adam with bf16 first moment (6 B/p
    #                                 optimizer state instead of 8)
    #   ALPA_TPU_BENCH_CE=chunked     chunked lm-head+CE (no fp32 logits)
    #   ALPA_TPU_BENCH_SHAPE=h2048l24 bigger model rung (gated by HBM est)
    # refuse typos OUTRIGHT: a silently-defaulted variant would burn a
    # scarce chip run while the result log claims the experiment ran
    opt_variant, ce_variant, shape_variant, bad = read_bench_variants()
    if bad:
        sys.exit(_refuse_variants(bad))

    if on_tpu:
        # GPT-1.3B-class config in bf16 (h2048 l16), batch 8 x seq 1024 —
        # the winner of the on-chip sweeps (scripts/bench_sweep.py).
        # XLA's fused attention beats the pallas flash kernel at seq 1024
        # (66.7 vs 47.7 on 125M); per-block remat is required to fit l16;
        # dense CE beats the chunked variant once logits fit (76.1 vs
        # 75.2).  Never raise batch above 8: the relay wedges.
        hidden, layers = BENCH_SHAPES[shape_variant]
        # head_dim 64 throughout (the sweep convention): comparable
        # numbers across shapes, and 64 tiles cleanly on the MXU
        config = GPTConfig(hidden_size=hidden, num_layers=layers,
                           num_heads=hidden // 64, seq_len=1024,
                           vocab_size=51200, dtype=jnp.bfloat16,
                           attention_impl="reference", remat_blocks=True)
        batch_size = 8
    else:
        config = GPTConfig(hidden_size=256, num_layers=4, num_heads=8,
                           seq_len=256, vocab_size=1024, dtype=jnp.float32)
        batch_size = 8

    opt_bytes = 6.0 if opt_variant == "bf16adam" else 8.0
    if on_tpu:
        est = estimate_hbm_gb(config, batch_size,
                              optimizer_bytes_per_param=opt_bytes,
                              chunked_ce=ce_variant == "chunked")
        if est > HBM_GATE_GB:
            print(json.dumps({
                "metric": "gpt_train_tflops_per_chip", "value": 0.0,
                "unit": "TFLOPS/chip", "vs_baseline": 0.0,
                "detail": {"error": f"refused: estimated {est:.1f} GB HBM "
                           f"> gate {HBM_GATE_GB} GB"},
            }))
            return

    alpa_tpu.init(cluster="local")
    model = GPTModel(config)
    rng = jax.random.PRNGKey(0)
    input_ids = jax.random.randint(rng, (batch_size, config.seq_len), 0,
                                   config.vocab_size)
    labels = jax.random.randint(rng, (batch_size, config.seq_len), 0,
                                config.vocab_size)
    params = model.init(rng, input_ids)
    if opt_variant == "bf16adam":
        # bf16 first moment: 2 B/p saved; the variance stays fp32
        tx = optax.adam(1e-4, mu_dtype=jnp.bfloat16)
    else:
        tx = optax.adam(1e-4)
    from flax.training import train_state
    state = train_state.TrainState.create(apply_fn=model.apply, params=params,
                                          tx=tx)

    @alpa_tpu.parallelize(method=alpa_tpu.ShardParallel(),
                          donate_argnums=(0,))
    def train_step(state, batch):

        def loss_fn(p):
            # dense CE beat chunked in the on-chip sweep at h2048 l16
            # (76.1 vs 75.2 TFLOPS); chunked is the variant that frees
            # the fp32 logits for bigger shape rungs
            return gpt_lm_loss(state.apply_fn, p, batch,
                               chunked=ce_variant == "chunked")

        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    batch = {"input_ids": input_ids, "labels": labels}

    # Warmup: first call compiles; the next two absorb one-time runtime
    # warmup (executable load / transfer setup on remote-attached chips).
    for _ in range(3):
        state, loss = train_step(state, batch)
        float(loss)  # force full completion

    n_iter = 10
    tic = time.perf_counter()
    for _ in range(n_iter):
        state, loss = train_step(state, batch)
    float(loss)  # drains the on-device queue
    latency = (time.perf_counter() - tic) / n_iter

    tokens_per_sec = batch_size * config.seq_len / latency
    tflops = compute_gpt_tflops(batch_size, config.seq_len, config.num_layers,
                                config.hidden_size, config.vocab_size, n_dev,
                                latency)
    # MFU against the detected generation's bf16 peak — the honest
    # number (vs_baseline divides by a V100's 37.01 for cross-framework
    # comparability with the reference recipe, which flatters a TPU).
    mfu = None
    if on_tpu:
        # the one MFU formula (ISSUE 9): telemetry.perf resolves the
        # peak from the device_peak_tflops knob or the detected
        # generation's TPU_GENERATION_SPECS entry
        from alpa_tpu.telemetry.perf import compute_mfu, peak_flops_info
        info = peak_flops_info()
        mfu = {"generation": info["generation"],
               "peak_bf16_tflops": info["peak_bf16_tflops"],
               "mfu": round(compute_mfu(tflops,
                                        info["peak_bf16_tflops"]), 4)}
    result = {
        "metric": "gpt_train_tflops_per_chip",
        "value": round(tflops, 3),
        "unit": "TFLOPS/chip",
        "vs_baseline": round(tflops / BASELINE_TFLOPS_PER_DEVICE, 4),
        "detail": {
            "model": f"h{config.hidden_size}-l{config.num_layers}",
            "opt": opt_variant,
            "ce": ce_variant,
            "batch": batch_size,
            "seq": config.seq_len,
            "latency_s": round(latency, 5),
            "tokens_per_sec": round(tokens_per_sec, 1),
            "n_devices": n_dev,
            "platform": devices[0].platform,
            **(mfu or {}),
        },
    }
    print(json.dumps(result))
    if on_tpu:
        try:  # keep a committed on-chip history next to the suites
            here = os.path.dirname(os.path.abspath(__file__))
            os.makedirs(os.path.join(here, "benchmark", "results"),
                        exist_ok=True)
            with open(os.path.join(here, "benchmark", "results",
                                   "onchip_log.jsonl"), "a") as f:
                f.write(json.dumps(result) + "\n")
        except OSError:
            pass


if __name__ == "__main__":
    if "--inner" in sys.argv:
        main()
    elif "--probe" in sys.argv:
        # single relay-health probe (used by scripts/chip_probe.sh so the
        # probe program has exactly one definition)
        sys.exit(0 if _probe_once() else 1)
    else:
        # validate variants HERE too: on a wedged chip the parent would
        # otherwise spend the whole window probing before the child
        # could report the typo
        _bad = read_bench_variants()[3]
        if _bad:
            sys.exit(_refuse_variants(_bad))
        budget = 1380.0
        for i, a in enumerate(sys.argv):
            if a == "--self-timeout" and i + 1 < len(sys.argv):
                budget = float(sys.argv[i + 1])
        sys.exit(_run_with_recovery(budget))
