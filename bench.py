"""Benchmark driver: GPT train-step throughput on the available chip(s).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

Baseline anchor (BASELINE.md): the reference's published manual-3D GPT-2.6B
result of 37.01 TFLOPS/GPU on 8x V100 (ref benchmark/alpa/README.md:89-101).
vs_baseline = achieved TFLOPS-per-chip / 37.01.

The remote-attached chip can wedge (observed: relay hangs on which even
trivial programs never complete).  Run with ``--self-timeout SECONDS``
(default 480) to guarantee a JSON line: the benchmark runs in a child
process; on timeout the parent reports the failure instead of hanging.
"""
import json
import os
import subprocess
import sys
import time

BASELINE_TFLOPS_PER_DEVICE = 37.01


def _run_with_timeout(timeout: float) -> int:
    cmd = [sys.executable, os.path.abspath(__file__), "--inner"]
    try:
        r = subprocess.run(cmd, timeout=timeout, capture_output=True,
                           text=True)
        # forward the child's (single) JSON line
        for line in (r.stdout or "").splitlines():
            if line.startswith("{"):
                print(line)
                return 0
        sys.stderr.write(r.stderr[-2000:] if r.stderr else "")
        print(json.dumps({
            "metric": "gpt_train_tflops_per_chip", "value": 0.0,
            "unit": "TFLOPS/chip", "vs_baseline": 0.0,
            "detail": {"error": "bench child produced no result",
                       "returncode": r.returncode},
        }))
        return 1
    except subprocess.TimeoutExpired:
        print(json.dumps({
            "metric": "gpt_train_tflops_per_chip", "value": 0.0,
            "unit": "TFLOPS/chip", "vs_baseline": 0.0,
            "detail": {"error": f"device unresponsive (> {timeout:.0f}s); "
                       "last good on-chip result: 76.06 TFLOPS/chip "
                       "(vs_baseline 2.055)"},
        }))
        return 1


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    import alpa_tpu
    from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
    from alpa_tpu.model.model_util import cross_entropy_loss
    from alpa_tpu.util import compute_gpt_tflops

    devices = jax.devices()
    on_tpu = devices[0].platform in ("tpu", "axon")
    n_dev = len(devices)

    if on_tpu:
        # GPT-1.3B-class config in bf16 (h2048 l16), batch 8 x seq 1024 —
        # the winner of the on-chip sweeps (scripts/bench_sweep.py):
        # 76.06 TFLOPS/chip.  Bigger models amortize dispatch overhead, so
        # MFU rises with size (125M: 66.7) until the remote compile helper
        # gives out (h2048 l24 / h2560 fail to compile).  XLA's fused
        # attention beats the pallas flash kernel at these shapes (66.7 vs
        # 47.7 on 125M) and per-block remat is required to fit l16 but
        # dense CE beats the chunked variant once logits fit (76.1 vs
        # 75.2).  Never raise batch above 8: the relay wedges.
        config = GPTConfig(hidden_size=2048, num_layers=16, num_heads=32,
                           seq_len=1024, vocab_size=51200,
                           dtype=jnp.bfloat16, attention_impl="reference",
                           remat_blocks=True)
        batch_size = 8
    else:
        config = GPTConfig(hidden_size=256, num_layers=4, num_heads=8,
                           seq_len=256, vocab_size=1024, dtype=jnp.float32)
        batch_size = 8

    alpa_tpu.init(cluster="local")
    model = GPTModel(config)
    rng = jax.random.PRNGKey(0)
    input_ids = jax.random.randint(rng, (batch_size, config.seq_len), 0,
                                   config.vocab_size)
    labels = jax.random.randint(rng, (batch_size, config.seq_len), 0,
                                config.vocab_size)
    params = model.init(rng, input_ids)
    tx = optax.adam(1e-4)
    from flax.training import train_state
    state = train_state.TrainState.create(apply_fn=model.apply, params=params,
                                          tx=tx)

    @alpa_tpu.parallelize(method=alpa_tpu.ShardParallel(),
                          donate_argnums=(0,))
    def train_step(state, batch):

        def loss_fn(p):
            # dense CE beat the chunked variant in the on-chip sweep
            # (76.1 vs 75.2 TFLOPS at h2048 l16); the fp32 logits fit
            logits = state.apply_fn(p, batch["input_ids"])
            return cross_entropy_loss(logits.astype(jnp.float32),
                                      batch["labels"])

        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    batch = {"input_ids": input_ids, "labels": labels}

    # Warmup: first call compiles; the next two absorb one-time runtime
    # warmup (executable load / transfer setup on remote-attached chips).
    for _ in range(3):
        state, loss = train_step(state, batch)
        float(loss)  # force full completion

    n_iter = 10
    tic = time.perf_counter()
    for _ in range(n_iter):
        state, loss = train_step(state, batch)
    float(loss)  # drains the on-device queue
    latency = (time.perf_counter() - tic) / n_iter

    tokens_per_sec = batch_size * config.seq_len / latency
    tflops = compute_gpt_tflops(batch_size, config.seq_len, config.num_layers,
                                config.hidden_size, config.vocab_size, n_dev,
                                latency)
    print(json.dumps({
        "metric": "gpt_train_tflops_per_chip",
        "value": round(tflops, 3),
        "unit": "TFLOPS/chip",
        "vs_baseline": round(tflops / BASELINE_TFLOPS_PER_DEVICE, 4),
        "detail": {
            "model": f"h{config.hidden_size}-l{config.num_layers}",
            "batch": batch_size,
            "seq": config.seq_len,
            "latency_s": round(latency, 5),
            "tokens_per_sec": round(tokens_per_sec, 1),
            "n_devices": n_dev,
            "platform": devices[0].platform,
        },
    }))


if __name__ == "__main__":
    if "--inner" in sys.argv:
        main()
    else:
        timeout = 480.0
        for i, a in enumerate(sys.argv):
            if a == "--self-timeout" and i + 1 < len(sys.argv):
                timeout = float(sys.argv[i + 1])
        sys.exit(_run_with_timeout(timeout))
