// OSDI'22 inter-op stage construction dynamic program, native implementation.
//
// Re-derivation of the algorithm driven by the reference's
// alpa/pipeline_parallel/stage_construction.py:235 (training_dp_impl, there
// numba-jit Python); this framework ships it as C++ (the reference keeps its
// heavy passes native too, SURVEY.md §2.9).
//
// Problem: split L contiguous layers into stages; give stage t a submesh
// from the choice list (n_m devices each) so submesh sizes sum to exactly D;
// minimize  sum_t cost_t + (B - 1) * max_t cost_t
// where cost_t = C[i][j][m] for layers i..j on submesh m and B = number of
// microbatches.  Solved by iterating candidate values of max_t cost_t
// (t_max) and, for each, a DP over (first uncovered layer, devices left,
// stages in the suffix) minimizing the total sum subject to every stage
// cost <= t_max.
//
// Memory feasibility is position-aware (the reference's max_n_succ_stages,
// stage_profiling.py:756): the s-th stage from the END holds some number of
// in-flight microbatches of activations that depends on the schedule, so
// the budget check for a candidate stage is
//   mem_param + inflight(s) * mem_act <= mem_budget
// which requires the suffix-stage count s as a DP dimension (the
// reference's f[s][layer][devices] state).  inflight_mode selects the
// schedule's in-flight profile:
//   0 = 1F1B:             min(s, B)
//   1 = GPipe:            B        (all microbatches live before backward)
//   2 = overlap-friendly: min(2s-1, B)  (eager forwards hold ~2x)
//   3 = inference:        1        (forward-only, nothing stacks)
//
// Exported C ABI (ctypes):
//   int stage_dp_abi_version() -> kAbiVersion (loader refuses a stale .so)
//   int stage_dp_solve(L, M, D, B, inflight_mode, C[L*L*M], n_devices[M],
//                      mem_param[L*L*M], mem_act[L*L*M], mem_budget,
//                      out_starts[L], out_meshes[L]) ->
//   number of stages (or -1 if infeasible). Stage t covers layers
//   out_starts[t] .. out_starts[t+1]-1 on submesh out_meshes[t].
#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <vector>

namespace {

constexpr double kInf = std::numeric_limits<double>::infinity();
constexpr int32_t kAbiVersion = 2;

double inflight_count(int s, int B, int32_t mode) {
  const int b = B > 0 ? B : 1;
  switch (mode) {
    case 1:  return b;                          // gpipe
    case 2:  return std::min(2 * s - 1, b);     // overlap-friendly 1f1b
    case 3:  return 1.0;                        // inference
    default: return std::min(s, b);             // 1f1b
  }
}

struct DPResult {
  double total;
  std::vector<int> starts;
  std::vector<int> meshes;
};

// DP for a fixed t_max: f[l][d][s] = min total cost covering layers l..L-1
// with exactly d devices left in exactly s stages.
bool run_dp(int L, int M, int D, int B, int32_t inflight_mode,
            const double* C, const int64_t* ndev,
            const double* mem_param, const double* mem_act,
            double mem_budget, double t_max, DPResult* out) {
  const int stride_j = M;
  const int stride_i = L * M;
  const int S = L + 1;
  std::vector<double> f(static_cast<size_t>(L + 1) * (D + 1) * S, kInf);
  std::vector<int32_t> choice_j(f.size(), -1);
  std::vector<int32_t> choice_m(f.size(), -1);
  auto idx = [D, S](int l, int d, int s) {
    return (static_cast<size_t>(l) * (D + 1) + d) * S + s;
  };
  f[idx(L, 0, 0)] = 0.0;

  for (int l = L - 1; l >= 0; --l) {
    for (int d = 1; d <= D; ++d) {
      for (int s = 1; s <= L - l; ++s) {
        double best = kInf;
        int bj = -1, bm = -1;
        // in-flight microbatches for the stage s-from-the-end
        const double inflight = inflight_count(s, B, inflight_mode);
        for (int j = l; j < L; ++j) {
          const double* row = C + l * stride_i + j * stride_j;
          const double* prow = mem_param + l * stride_i + j * stride_j;
          const double* arow = mem_act + l * stride_i + j * stride_j;
          for (int m = 0; m < M; ++m) {
            const int64_t n = ndev[m];
            if (n > d) continue;
            const double c = row[m];
            if (c > t_max || c >= kInf) continue;
            if (mem_budget > 0 &&
                prow[m] + inflight * arow[m] > mem_budget)
              continue;
            const double rest =
                f[idx(j + 1, d - static_cast<int>(n), s - 1)];
            if (rest >= kInf) continue;
            const double tot = c + rest;
            if (tot < best) {
              best = tot;
              bj = j;
              bm = m;
            }
          }
        }
        f[idx(l, d, s)] = best;
        choice_j[idx(l, d, s)] = bj;
        choice_m[idx(l, d, s)] = bm;
      }
    }
  }
  double best_total = kInf;
  int best_s = -1;
  for (int s = 1; s <= L; ++s) {
    if (f[idx(0, D, s)] < best_total) {
      best_total = f[idx(0, D, s)];
      best_s = s;
    }
  }
  if (best_s < 0) return false;

  out->total = best_total;
  out->starts.clear();
  out->meshes.clear();
  int l = 0, d = D, s = best_s;
  while (l < L) {
    const int j = choice_j[idx(l, d, s)];
    const int m = choice_m[idx(l, d, s)];
    if (j < 0 || m < 0) return false;
    out->starts.push_back(l);
    out->meshes.push_back(m);
    d -= static_cast<int>(ndev[m]);
    l = j + 1;
    s -= 1;
  }
  return d == 0 && s == 0;
}

}  // namespace

extern "C" {

int32_t stage_dp_abi_version() { return kAbiVersion; }

int stage_dp_solve(int32_t L, int32_t M, int32_t D, int32_t B,
                   int32_t inflight_mode,
                   const double* C, const int64_t* n_devices,
                   const double* mem_param, const double* mem_act,
                   double mem_budget, int32_t* out_starts,
                   int32_t* out_meshes) {
  if (L <= 0 || M <= 0 || D <= 0) return -1;
  // Candidate t_max values: every distinct finite stage cost.
  std::vector<double> candidates;
  candidates.reserve(static_cast<size_t>(L) * L * M);
  for (int i = 0; i < L; ++i)
    for (int j = i; j < L; ++j)
      for (int m = 0; m < M; ++m) {
        const double c = C[(i * L + j) * M + m];
        if (c < kInf) candidates.push_back(c);
      }
  if (candidates.empty()) return -1;
  std::sort(candidates.begin(), candidates.end());
  candidates.erase(std::unique(candidates.begin(), candidates.end()),
                   candidates.end());

  double best_obj = kInf;
  DPResult best;
  DPResult cur;
  for (double t_max : candidates) {
    if (best_obj < kInf && (B - 1) * t_max >= best_obj) break;
    if (!run_dp(L, M, D, B, inflight_mode, C, n_devices, mem_param, mem_act,
                mem_budget, t_max, &cur))
      continue;
    const double obj = cur.total + (B - 1) * t_max;
    if (obj < best_obj) {
      best_obj = obj;
      best = cur;
    }
  }
  if (best_obj >= kInf) return -1;
  const int S = static_cast<int>(best.starts.size());
  for (int t = 0; t < S; ++t) {
    out_starts[t] = best.starts[t];
    out_meshes[t] = best.meshes[t];
  }
  return S;
}

}  // extern "C"
