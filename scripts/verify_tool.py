"""Static plan verifier + repo lint front-end (ISSUE 8).

Usage::

    python scripts/verify_tool.py verify plan [--dir DIR] [--all] [--json]
    python scripts/verify_tool.py verify lint [--json]

``verify plan`` prints the cached :class:`PlanVerdict` of every lowered
register-file program found in the compile cache's disk tier — WITHOUT
recompiling anything (the verifier caches verdicts under the
``plan_verdict`` namespace at lowering time; this just reads them
back).  The cache directory comes from ``--dir``, else
``ALPA_TPU_CACHE_DIR``.  Default shows the newest verdict; ``--all``
shows every cached one.  Exit status 1 when any shown verdict has
errors.

``verify lint`` runs the AST repo lint (``alpa_tpu.analysis.lint``) —
config-knob env/doc coverage, metric naming, deprecated-timer imports,
fault-site registry — and exits 1 on any violation.  The same lint
gates tier-1 via ``tests/util/test_repo_lint.py``.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _age(mtime: float) -> str:
    s = time.time() - mtime
    for unit, div in (("d", 86400), ("h", 3600), ("m", 60)):
        if s >= div:
            return f"{s / div:.1f}{unit}"
    return f"{s:.0f}s"


def cmd_plan(args):
    from alpa_tpu.analysis import plan_verifier
    cache = None
    if args.dir:
        from alpa_tpu.compile_cache import CompileCache
        cache = CompileCache(cache_dir=args.dir)
    cached = plan_verifier.load_cached_verdicts(cache)
    if not cached:
        where = args.dir or os.environ.get("ALPA_TPU_CACHE_DIR") or (
            "(memory only — set ALPA_TPU_CACHE_DIR)")
        sys.exit(f"no cached plan verdicts in {where}; verdicts are "
                 f"written at compile time when verify_plans != off")
    shown = cached if args.all else cached[:1]
    if args.json:
        print(json.dumps([{"key": e["key"], "mtime": e["mtime"],
                           "verdict": e["verdict"].to_dict()}
                          for e in shown], indent=2, sort_keys=True))
    else:
        for e in shown:
            print(f"== plan {e['key'][:16]}..  "
                  f"(compiled {_age(e['mtime'])} ago) ==")
            print(e["verdict"].format_table())
            print()
        if not args.all and len(cached) > 1:
            print(f"({len(cached) - 1} older verdict(s) cached; "
                  f"--all to show)")
    if any(not e["verdict"].ok for e in shown):
        sys.exit(1)


def cmd_lint(args):
    from alpa_tpu.analysis import lint
    violations = lint.run_lint()
    if args.json:
        print(json.dumps([v.__dict__ for v in violations], indent=2,
                         sort_keys=True))
    else:
        print(lint.format_report(violations))
    if violations:
        sys.exit(1)


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)
    verify = sub.add_parser(
        "verify", help="static verification entry point")
    vsub = verify.add_subparsers(dest="what", required=True)
    p = vsub.add_parser(
        "plan", help="print cached plan verdicts (no recompilation)")
    p.add_argument("--dir", default=None,
                   help="compile cache dir (default: $ALPA_TPU_CACHE_DIR)")
    p.add_argument("--all", action="store_true",
                   help="show every cached verdict, not just the newest")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_plan)
    l = vsub.add_parser("lint", help="run the AST repo lint")
    l.add_argument("--json", action="store_true")
    l.set_defaults(fn=cmd_lint)
    args = parser.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()
