"""Static plan verifier + repo lint front-end (ISSUE 8, ISSUE 13).

Usage::

    python scripts/verify_tool.py verify plan [--dir DIR] [--all] [--json]
    python scripts/verify_tool.py verify zero-delta [--dir DIR]
                                                    [--a KEY --b KEY] [--json]
    python scripts/verify_tool.py verify diff [--dir DIR]
                                              [--a KEY --b KEY] [--json]
    python scripts/verify_tool.py verify lint [--json]
    python scripts/verify_tool.py modelcheck [--fixture PATH]
                                             [--budget N] [--json]
    python scripts/verify_tool.py numerics [--fixture PATH]
                                           [--error-budget F] [--json]
    python scripts/verify_tool.py equiv [--fixture PATH]
                                        [--term-budget N] [--json]

``verify plan`` prints the cached :class:`PlanVerdict` of every lowered
register-file program found in the compile cache's disk tier — WITHOUT
recompiling anything (the verifier caches verdicts under the
``plan_verdict`` namespace at lowering time; this just reads them
back).  The cache directory comes from ``--dir``, else
``ALPA_TPU_CACHE_DIR``.  Default shows the newest verdict; ``--all``
shows every cached one.  Exit status 1 when any shown verdict has
at least one error-severity finding.

``verify plan --json`` emits the **stable** machine-readable schema
``alpa-plan-verdict/v1``::

    {"schema": "alpa-plan-verdict/v1",
     "analyses": ["typing", "deadlock", "liveness", "structure",
                  "model_check"],
     "plans": [
       {"key": "<cache key>",          # hex fingerprint-derived key
        "mtime": 1712345678.9,         # verdict file mtime (epoch s)
        "ok": true,                    # no error-severity findings
        "verdict": {
          "version": 3,                # ANALYSES_VERSION
          "errors":   [{"analysis", "code", "message", "op"}...],
          "warnings": [...same shape...],
          "notes":    [...same shape...],
          "stats": {..., "model_check": {  # present when the model
            "states": N,                   # checker ran on this plan
            "transitions": N, "por_commits": N,
            "reduction_ratio": 0.33, "partial": false,
            "semantics": {"buffered": "pass", "rendezvous": "pass"},
            "declared_window": 2, "max_inflight": 2,
            "retry_sites": {"<site>": {"classification":
                "safe|unsafe|unreachable", "reasons": [...],
                "hooks": N}, ...},
            "counterexample": [...schedule lines...] or null}}}}]}

Fields are only ever added, never renamed or removed, within /v1.

``modelcheck`` runs the explicit-state model checker (ISSUE 13,
``alpa_tpu.analysis.model_check``) standalone on a serialized plan
fixture (format ``alpa-model-check-plan/v1``; default: the committed
2-mesh overlap fixture under ``benchmark/results/``) and prints
states explored, partial-order reduction ratio, per-property
verdicts under both channel semantics, retry-site classification,
and — on failure — the counterexample instruction schedule.  Exit
status 1 on any error-severity finding.

``numerics`` runs the numerics certification (ISSUE 14,
``alpa_tpu.analysis.numerics``) standalone on a serialized plan
fixture (same ``alpa-model-check-plan/v1`` serialization; default: the
committed 2-mesh quantized-edge fixture under ``benchmark/results/``)
and prints the per-output composed error-bound table, the lossy-hop
enumeration, and every ``numerics.*`` finding.  Exit status 1 on any
error-severity finding.  ``--json`` emits the **stable** schema
``alpa-numerics/v1``::

    {"schema": "alpa-numerics/v1",
     "fixture": "<path>",
     "ok": true,                       # no error-severity findings
     "findings": [{"analysis", "code", "message", "op",
                   "severity"}...],
     "stats": {"max_error_bound": 0.0079,
               "lossy_edges": {"int8": 2},     # hops by codec kind
               "n_lossy_collectives": 2, "n_bf16_reductions": 0,
               "bound_table": [{"slot", "var", "provenance",
                                "storage", "accum", "bound",
                                "hops"}...],   # program outputs
               "budget": 0.05, "n_tracked": N, "seconds": 0.001}}

Fields are only ever added, never renamed or removed, within /v1.

``equiv`` runs the translation validation (ISSUE 15,
``alpa_tpu.analysis.equivalence``) standalone on a serialized plan
fixture (same ``alpa-model-check-plan/v1`` serialization, which must
embed a ``reference`` program; default: the committed 2-mesh
4-microbatch fixture under ``benchmark/results/``) and prints the
per-output proof table, axioms used, and every ``equiv.*`` finding
with its term-diff witness.  Exit status 1 on any error-severity
finding.  ``--json`` emits the **stable** schema ``alpa-equiv/v1``::

    {"schema": "alpa-equiv/v1",
     "fixture": "<path>",
     "ok": true,                       # no error-severity findings
     "findings": [{"analysis", "code", "message", "op",
                   "severity"}...],
     "stats": {"n_terms": N, "n_outputs": N, "n_proved": N,
               "n_apps": N, "num_microbatches": N,
               "axioms_used": ["accumulation-reassociation", ...],
               "per_output": [{"var", "instance", "mesh", "slot",
                               "axioms", "status",
                               "witness"?}...],  # protected outputs
               "budget": 100000, "partial": false,
               "seconds": 0.001}}

Fields are only ever added, never renamed or removed, within /v1.

``verify lint`` runs the AST repo lint (``alpa_tpu.analysis.lint``) —
config-knob env/doc coverage, metric naming, deprecated-timer imports,
fault-site registry — and exits 1 on any violation.  The same lint
gates tier-1 via ``tests/util/test_repo_lint.py``.

``verify zero-delta`` compares two cached verdicts' static per-mesh
byte accounting — compile the same program once under ``zero_stage=0``
and once under ``zero_stage=2`` into the same cache dir, then run this
to see what the sharded weight-update layout saves: per-mesh
``peak_bytes`` delta, per-mesh ``opt_state_bytes`` ratio, and the
verifier's ``zero_bytes_saved`` total (docs/performance.md).  Defaults
to the two newest verdicts; ``--a``/``--b`` select by key prefix.

``verify diff`` diffs two cached verdicts with the exact
``(analysis, code)``-set semantics the certified-superoptimization
acceptance gate uses (ISSUE 17; one diff implementation —
``alpa_tpu.analysis.superopt.verdict_diff`` — shared with the engine):
new findings, resolved findings, and the ACCEPT/REJECT verdict the
gate would reach.  Exit status 1 on REJECT.  Defaults to newest-vs-
second-newest (older = baseline); ``--a``/``--b`` select by prefix.
"""
import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _age(mtime: float) -> str:
    s = time.time() - mtime
    for unit, div in (("d", 86400), ("h", 3600), ("m", 60)):
        if s >= div:
            return f"{s / div:.1f}{unit}"
    return f"{s:.0f}s"


def cmd_plan(args):
    cached = _load_verdicts(args)
    if not cached:
        where = args.dir or os.environ.get("ALPA_TPU_CACHE_DIR") or (
            "(memory only — set ALPA_TPU_CACHE_DIR)")
        sys.exit(f"no cached plan verdicts in {where}; verdicts are "
                 f"written at compile time when verify_plans != off")
    shown = cached if args.all else cached[:1]
    if args.json:
        from alpa_tpu.analysis import plan_verifier
        print(json.dumps(
            {"schema": "alpa-plan-verdict/v1",
             "analyses": list(plan_verifier.ANALYSES),
             "plans": [{"key": e["key"], "mtime": e["mtime"],
                        "ok": e["verdict"].ok,
                        "verdict": e["verdict"].to_dict()}
                       for e in shown]},
            indent=2, sort_keys=True))
    else:
        for e in shown:
            print(f"== plan {e['key'][:16]}..  "
                  f"(compiled {_age(e['mtime'])} ago) ==")
            print(e["verdict"].format_table())
            print()
        if not args.all and len(cached) > 1:
            print(f"({len(cached) - 1} older verdict(s) cached; "
                  f"--all to show)")
    if any(not e["verdict"].ok for e in shown):
        sys.exit(1)


def _load_verdicts(args):
    from alpa_tpu.analysis import plan_verifier
    cache = None
    if args.dir:
        from alpa_tpu.compile_cache import CompileCache
        cache = CompileCache(cache_dir=args.dir)
    return plan_verifier.load_cached_verdicts(cache)


def _pick(cached, prefix, label):
    hits = [e for e in cached if e["key"].startswith(prefix)]
    if not hits:
        sys.exit(f"no cached verdict with key prefix {prefix!r} "
                 f"for {label}")
    return hits[0]


def cmd_zero_delta(args):
    cached = _load_verdicts(args)
    if len(cached) < 2:
        sys.exit(f"need two cached verdicts to diff, found "
                 f"{len(cached)}; compile the program under "
                 f"zero_stage=0 and zero_stage=2 with "
                 f"ALPA_TPU_CACHE_DIR set")
    if args.a or args.b:
        if not (args.a and args.b):
            sys.exit("--a and --b must be given together")
        ea, eb = _pick(cached, args.a, "--a"), _pick(cached, args.b,
                                                    "--b")
    else:
        eb, ea = cached[0], cached[1]  # newest last-compiled = sharded
    sa, sb = ea["verdict"].stats, eb["verdict"].stats
    # orient so `a` is the replicated (more opt-state bytes) plan
    if sum(sa.get("opt_state_bytes", {}).values()) < \
            sum(sb.get("opt_state_bytes", {}).values()):
        ea, eb, sa, sb = eb, ea, sb, sa
    meshes = sorted(set(sa.get("peak_bytes", {}))
                    | set(sb.get("peak_bytes", {})), key=str)
    rows = []
    for m in meshes:
        pa = float(sa.get("peak_bytes", {}).get(m, 0.0))
        pb = float(sb.get("peak_bytes", {}).get(m, 0.0))
        oa = float(sa.get("opt_state_bytes", {}).get(m, 0.0))
        ob = float(sb.get("opt_state_bytes", {}).get(m, 0.0))
        rows.append({"mesh": str(m), "peak_bytes_a": pa,
                     "peak_bytes_b": pb, "peak_delta": pa - pb,
                     "opt_state_bytes_a": oa, "opt_state_bytes_b": ob,
                     "opt_state_ratio":
                         round(oa / ob, 4) if ob else None})
    result = {"plan_a": {"key": ea["key"], "mtime": ea["mtime"]},
              "plan_b": {"key": eb["key"], "mtime": eb["mtime"]},
              "per_mesh": rows,
              "zero_bytes_saved_b":
                  float(sb.get("zero_bytes_saved", 0.0))}
    if args.json:
        print(json.dumps(result, indent=2, sort_keys=True))
        return
    print(f"plan a (replicated opt state): {ea['key'][:16]}..  "
          f"(compiled {_age(ea['mtime'])} ago)")
    print(f"plan b (sharded opt state):    {eb['key'][:16]}..  "
          f"(compiled {_age(eb['mtime'])} ago)")
    print(f"{'mesh':<6} {'peak a':>12} {'peak b':>12} {'delta':>12} "
          f"{'opt a':>12} {'opt b':>12} {'opt ratio':>10}")
    for r in rows:
        ratio = (f"{r['opt_state_ratio']:.2f}x"
                 if r["opt_state_ratio"] is not None else "-")
        print(f"{r['mesh']:<6} {r['peak_bytes_a']:>12.0f} "
              f"{r['peak_bytes_b']:>12.0f} {r['peak_delta']:>12.0f} "
              f"{r['opt_state_bytes_a']:>12.0f} "
              f"{r['opt_state_bytes_b']:>12.0f} {ratio:>10}")
    print(f"plan b zero sharding saves "
          f"{result['zero_bytes_saved_b']:.0f} B/device vs replicated")


DEFAULT_FIXTURE = os.path.join(
    REPO, "benchmark", "results", "model_check_fixture_plan.json")
DEFAULT_NUMERICS_FIXTURE = os.path.join(
    REPO, "benchmark", "results", "numerics_fixture_plan.json")
DEFAULT_EQUIV_FIXTURE = os.path.join(
    REPO, "benchmark", "results", "equiv_fixture_plan.json")


def cmd_modelcheck(args):
    from alpa_tpu.analysis import model_check as mc
    try:
        model, hooks, window = mc.load_fixture(args.fixture)
    except (OSError, ValueError, KeyError) as e:
        sys.exit(f"cannot load model-check fixture {args.fixture}: {e}")
    budget = args.budget or mc.DEFAULT_STATE_BUDGET
    result = mc.check_model(model, hooks=hooks, overlap_window=window,
                            budget=budget)
    if args.json:
        print(json.dumps(
            {"schema": "alpa-model-check/v1",
             "fixture": args.fixture,
             "ok": result.ok,
             "findings": [dict(f.to_dict(),
                               severity=mc.severity_of(f.code))
                          for f in result.findings],
             "stats": result.stats},
            indent=2, sort_keys=True, default=str))
    else:
        print(f"fixture: {args.fixture}")
        print(result.format())
    if not result.ok:
        sys.exit(1)


def cmd_numerics(args):
    from alpa_tpu.analysis import model_check as mc
    from alpa_tpu.analysis import numerics as num
    try:
        model, hooks, _window = mc.load_fixture(args.fixture)
    except (OSError, ValueError, KeyError) as e:
        sys.exit(f"cannot load plan fixture {args.fixture}: {e}")
    result = num.check_numerics(model, hooks=hooks,
                                budget=args.error_budget)
    if args.json:
        print(json.dumps(
            {"schema": "alpa-numerics/v1",
             "fixture": args.fixture,
             "ok": result.ok,
             "findings": [dict(f.to_dict(),
                               severity=num.severity_of(f.code))
                          for f in result.findings],
             "stats": result.stats},
            indent=2, sort_keys=True, default=str))
    else:
        print(f"fixture: {args.fixture}")
        print(result.format())
    if not result.ok:
        sys.exit(1)


def cmd_equiv(args):
    from alpa_tpu.analysis import equivalence as eq
    from alpa_tpu.analysis import model_check as mc
    try:
        model, hooks, _window = mc.load_fixture(args.fixture)
    except (OSError, ValueError, KeyError) as e:
        sys.exit(f"cannot load plan fixture {args.fixture}: {e}")
    if model.reference is None:
        sys.exit(f"fixture {args.fixture} embeds no reference program; "
                 f"translation validation needs one (serialize the "
                 f"model with build_model(..., reference=...))")
    result = eq.check_equiv(model, hooks=hooks, budget=args.term_budget)
    if args.json:
        print(json.dumps(
            {"schema": "alpa-equiv/v1",
             "fixture": args.fixture,
             "ok": result.ok,
             "findings": [dict(f.to_dict(),
                               severity=eq.severity_of(f.code))
                          for f in result.findings],
             "stats": result.stats},
            indent=2, sort_keys=True, default=str))
    else:
        print(f"fixture: {args.fixture}")
        print(result.format())
    if not result.ok:
        sys.exit(1)


def cmd_diff(args):
    """Diff two cached verdicts with the exact ``(analysis, code)``-set
    semantics the superopt acceptance gate uses (ISSUE 17;
    ``alpa_tpu.analysis.superopt.verdict_diff`` is the one diff
    implementation, shared with the engine)."""
    from alpa_tpu.analysis.superopt import verdict_diff
    cached = _load_verdicts(args)
    if len(cached) < 2:
        sys.exit(f"need two cached verdicts to diff, found "
                 f"{len(cached)}; set ALPA_TPU_CACHE_DIR and compile "
                 f"both plans into it")
    if args.a or args.b:
        if not (args.a and args.b):
            sys.exit("--a and --b must be given together")
        ea, eb = _pick(cached, args.a, "--a"), _pick(cached, args.b,
                                                     "--b")
    else:
        eb, ea = cached[0], cached[1]     # older = baseline
    diff = verdict_diff(ea["verdict"], eb["verdict"])
    diff["baseline_key"] = ea["key"]
    diff["candidate_key"] = eb["key"]
    if args.json:
        print(json.dumps({"schema": "alpa-verdict-diff/v1", **diff},
                         indent=2, sort_keys=True))
    else:
        print(f"baseline  {ea['key'][:16]}..  "
              f"({len(diff['baseline_findings'])} findings)")
        print(f"candidate {eb['key'][:16]}..  "
              f"({len(diff['candidate_findings'])} findings)")
        print(f"new findings (gate-rejecting): "
              f"{', '.join(diff['new']) or '(none)'}")
        print(f"resolved findings: "
              f"{', '.join(diff['resolved']) or '(none)'}")
        print(f"gate verdict: "
              f"{'ACCEPT' if diff['ok'] else 'REJECT'}")
    if not diff["ok"]:
        sys.exit(1)


def cmd_lint(args):
    from alpa_tpu.analysis import lint
    violations = lint.run_lint()
    if args.json:
        print(json.dumps([v.__dict__ for v in violations], indent=2,
                         sort_keys=True))
    else:
        print(lint.format_report(violations))
    if violations:
        sys.exit(1)


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)
    verify = sub.add_parser(
        "verify", help="static verification entry point")
    vsub = verify.add_subparsers(dest="what", required=True)
    p = vsub.add_parser(
        "plan", help="print cached plan verdicts (no recompilation)")
    p.add_argument("--dir", default=None,
                   help="compile cache dir (default: $ALPA_TPU_CACHE_DIR)")
    p.add_argument("--all", action="store_true",
                   help="show every cached verdict, not just the newest")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_plan)
    z = vsub.add_parser(
        "zero-delta",
        help="per-mesh peak/opt-state byte delta between a replicated "
             "and a ZeRO-sharded cached plan verdict")
    z.add_argument("--dir", default=None,
                   help="compile cache dir (default: $ALPA_TPU_CACHE_DIR)")
    z.add_argument("--a", default=None,
                   help="key prefix of the replicated (zero_stage=0) plan")
    z.add_argument("--b", default=None,
                   help="key prefix of the sharded (zero_stage=2) plan")
    z.add_argument("--json", action="store_true")
    z.set_defaults(fn=cmd_zero_delta)
    d = vsub.add_parser(
        "diff",
        help="diff two cached verdicts with the superopt acceptance "
             "gate's (analysis, code)-set semantics (ISSUE 17)")
    d.add_argument("--dir", default=None,
                   help="compile cache dir (default: $ALPA_TPU_CACHE_DIR)")
    d.add_argument("--a", default=None,
                   help="key prefix of the baseline verdict")
    d.add_argument("--b", default=None,
                   help="key prefix of the candidate verdict")
    d.add_argument("--json", action="store_true")
    d.set_defaults(fn=cmd_diff)
    l = vsub.add_parser("lint", help="run the AST repo lint")
    l.add_argument("--json", action="store_true")
    l.set_defaults(fn=cmd_lint)
    m = sub.add_parser(
        "modelcheck",
        help="model-check a serialized plan fixture "
             "(alpa-model-check-plan/v1) standalone")
    m.add_argument("--fixture", default=DEFAULT_FIXTURE,
                   help="fixture JSON path (default: the committed "
                        "2-mesh overlap fixture)")
    m.add_argument("--budget", type=int, default=None,
                   help="state-count budget (default: "
                        "model_check.DEFAULT_STATE_BUDGET)")
    m.add_argument("--json", action="store_true")
    m.set_defaults(fn=cmd_modelcheck)
    u = sub.add_parser(
        "numerics",
        help="run the numerics certification on a serialized plan "
             "fixture (alpa-model-check-plan/v1) standalone")
    u.add_argument("--fixture", default=DEFAULT_NUMERICS_FIXTURE,
                   help="fixture JSON path (default: the committed "
                        "2-mesh quantized-edge fixture)")
    u.add_argument("--error-budget", type=float, default=None,
                   help="per-tensor relative-error budget (default: "
                        "numerics.DEFAULT_ERROR_BUDGET)")
    u.add_argument("--json", action="store_true")
    u.set_defaults(fn=cmd_numerics)
    e = sub.add_parser(
        "equiv",
        help="run the translation validation on a serialized plan "
             "fixture (alpa-model-check-plan/v1 with an embedded "
             "reference program) standalone")
    e.add_argument("--fixture", default=DEFAULT_EQUIV_FIXTURE,
                   help="fixture JSON path (default: the committed "
                        "2-mesh 4-microbatch fixture)")
    e.add_argument("--term-budget", type=int, default=None,
                   help="hash-consed term budget (default: "
                        "equivalence.DEFAULT_TERM_BUDGET)")
    e.add_argument("--json", action="store_true")
    e.set_defaults(fn=cmd_equiv)
    args = parser.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()
