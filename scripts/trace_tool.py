"""Work with unified telemetry traces (alpa_tpu.telemetry, ISSUE 5).

Usage::

    python scripts/trace_tool.py record  OUT.json -- CMD [ARGS...]
    python scripts/trace_tool.py merge   OUT.json TRACE.json [TRACE.json...]
    python scripts/trace_tool.py summarize TRACE.json [--top N] [--bubbles]
                                           [--edges]
    python scripts/trace_tool.py top     TRACE.json [--top N]
    python scripts/trace_tool.py flight  FLIGHT.json [--last N]

``record`` runs CMD as a child process with ``ALPA_TPU_TRACE=1`` and
``ALPA_TPU_TRACE_DIR`` pointed at a scratch dir, then merges whatever
trace files the run saved into OUT.json; ``merge`` combines per-mesh /
per-process trace files onto distinct pids (each input keeps its own
track group in Perfetto); ``summarize`` prints total time per category,
per-track busy/idle/span-count columns, and the longest individual
spans — ``--bubbles`` additionally runs the step perf analyzer
(``alpa_tpu.telemetry.perf`` / ``scripts/perf_tool.py``, ISSUE 9) for
per-mesh bubble fractions — and ``--edges`` a per-reshard-edge wire
table (strategy, bytes, wire us, achieved GB/s from ``reshard.wire``
spans: the human-readable view of exactly what the calibration store
ingests, ISSUE 12); ``top`` aggregates spans by name (hottest
instructions first).  All outputs load directly in
https://ui.perfetto.dev.

``flight`` pretty-prints a flight-recorder dump (ISSUE 6): the ring of
last-N instruction events the runtime auto-saves when a step raises, a
fault site fires, or the watchdog declares a mesh SUSPECT.  Dumps come
from ``dump_debug_info`` (``flight.json``) or the auto-dump path logged
at WARNING level (``alpa_flight_<pid>_<seq>.json``).
"""
import argparse
import collections
import json
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from alpa_tpu.telemetry.trace import merge_chrome_traces  # noqa: E402


def _load(path):
    with open(path, encoding="utf-8") as f:
        trace = json.load(f)
    if "traceEvents" not in trace:
        sys.exit(f"{path}: not a chrome trace (no traceEvents)")
    return trace


def _duration_events(trace):
    """Complete spans as (name, category, dur_us) from B/E pairs."""
    open_stacks = collections.defaultdict(list)
    spans = []
    events = sorted(
        (e for e in trace["traceEvents"] if e.get("ph") in ("B", "E")),
        key=lambda e: (e["ts"], 0 if e["ph"] == "E" else 1))
    for e in events:
        key = (e.get("pid", 0), e.get("tid", 0))
        if e["ph"] == "B":
            open_stacks[key].append(e)
        elif open_stacks[key]:
            b = open_stacks[key].pop()
            spans.append((b["name"], b.get("cat", ""), e["ts"] - b["ts"]))
    return spans


def cmd_record(args):
    if not args.cmd:
        sys.exit("record needs a command: trace_tool.py record OUT -- CMD")
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    with tempfile.TemporaryDirectory(prefix="alpa-trace-") as scratch:
        env = dict(os.environ,
                   ALPA_TPU_TRACE="1",
                   ALPA_TPU_TRACE_DIR=scratch)
        ret = subprocess.call(cmd, env=env)
        traces = sorted(
            os.path.join(scratch, f) for f in os.listdir(scratch)
            if f.endswith(".json"))
        if not traces:
            sys.exit(f"command exited {ret} but saved no trace files "
                     f"into ALPA_TPU_TRACE_DIR ({scratch})")
        merged = merge_chrome_traces([_load(p) for p in traces])
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(merged, f)
        print(f"merged {len(traces)} trace file(s) -> {args.out} "
              f"({len(merged['traceEvents'])} events)")
    if ret:
        sys.exit(ret)


def cmd_merge(args):
    traces = [_load(p) for p in args.traces]
    merged = merge_chrome_traces(traces)
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(merged, f)
    print(f"merged {len(traces)} trace file(s) -> {args.out} "
          f"({len(merged['traceEvents'])} events)")


def cmd_summarize(args):
    trace = _load(args.trace)
    spans = _duration_events(trace)
    if not spans:
        print("no complete spans in trace")
        return
    per_cat = collections.Counter()
    for _name, cat, dur in spans:
        per_cat[cat or "(none)"] += dur
    print(f"{len(spans)} spans, "
          f"{len(trace['traceEvents'])} raw events")
    print(f"\n{'category':<16} {'total ms':>12} {'share':>7}")
    total = sum(per_cat.values()) or 1.0
    for cat, us in per_cat.most_common():
        print(f"{cat:<16} {us / 1e3:>12.3f} {us / total:>6.1%}")
    # per-track busy/idle against the trace's overall envelope (ISSUE 9)
    from alpa_tpu.telemetry.perf import spans_from_chrome
    tracked = spans_from_chrome(trace)
    if tracked:
        t0 = min(s["ts_us"] for s in tracked)
        t1 = max(s["ts_us"] + s["dur_us"] for s in tracked)
        envelope = max(t1 - t0, 1e-9)
        per_track = collections.defaultdict(lambda: [0, 0.0])
        for s in tracked:
            per_track[s["track"]][0] += 1
            per_track[s["track"]][1] += s["dur_us"]
        print(f"\n{'track':<20} {'spans':>7} {'busy ms':>12} "
              f"{'idle ms':>12} {'busy':>7}")
        for track, (n, busy) in sorted(per_track.items(),
                                       key=lambda kv: -kv[1][1]):
            idle = max(0.0, envelope - busy)
            print(f"{track:<20} {n:>7} {busy / 1e3:>12.3f} "
                  f"{idle / 1e3:>12.3f} {busy / envelope:>6.1%}")
    print(f"\ntop {args.top} longest spans:")
    for name, cat, dur in sorted(spans, key=lambda s: -s[2])[:args.top]:
        print(f"  {dur / 1e3:>10.3f} ms  [{cat}] {name}")
    if args.bubbles:
        from alpa_tpu.telemetry.perf import report_from_trace
        report = report_from_trace(trace)
        if report is None:
            print("\n--bubbles: no analyzable step (no mesh-track "
                  "instruction/transfer spans)")
        else:
            print(f"\n{report.format_text(top=args.top)}")
    if args.edges:
        from alpa_tpu.telemetry import perf as _perf
        from alpa_tpu.telemetry import calibration as _cal
        joined = _perf._join_spans(tracked, None)
        if joined is None:
            print("\n--edges: no analyzable step (no mesh-track "
                  "instruction/transfer spans)")
        else:
            print("\nreshard edges (wire legs, what the calibration "
                  "store ingests):")
            print(_cal.format_edge_table(_cal.edge_wire_table(joined)))


def cmd_top(args):
    trace = _load(args.trace)
    spans = _duration_events(trace)
    if not spans:
        print("no complete spans in trace")
        return
    agg = collections.defaultdict(lambda: [0, 0.0])
    for name, _cat, dur in spans:
        agg[name][0] += 1
        agg[name][1] += dur
    print(f"{'total ms':>12} {'count':>7} {'avg ms':>10}  name")
    ranked = sorted(agg.items(), key=lambda kv: -kv[1][1])[:args.top]
    for name, (n, us) in ranked:
        print(f"{us / 1e3:>12.3f} {n:>7} {us / n / 1e3:>10.3f}  {name}")


def cmd_flight(args):
    from alpa_tpu.telemetry.flight import load_dump  # noqa: E402
    try:
        dump = load_dump(args.dump)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        sys.exit(f"{args.dump}: {e}")
    events = dump["events"]
    print(f"flight dump: {args.dump}")
    print(f"  reason:    {dump.get('reason') or '(manual)'}")
    print(f"  capacity:  {dump.get('capacity')}  "
          f"events: {len(events)}  "
          f"seq: {dump.get('first_seq')}..{dump.get('last_seq')}")
    if not events:
        print("  (empty ring)")
        return
    show = events[-args.last:] if args.last else events
    if len(show) < len(events):
        print(f"  showing last {len(show)} of {len(events)}")
    t_end = max(e["t_end_us"] for e in events)
    print(f"\n{'seq':>6} {'t-end':>9} {'dur ms':>9} {'mesh':>4} "
          f"{'node':>5} {'kind':<7} {'outcome':<10} name")
    for e in show:
        dur_ms = (e["t_end_us"] - e["t_start_us"]) / 1e3
        rel_ms = (e["t_end_us"] - t_end) / 1e3
        slots = ""
        if e.get("slots"):
            s = ",".join(str(x) for x in e["slots"][:4])
            more = len(e["slots"]) - 4
            slots = f"  [slots {s}{f',+{more}' if more > 0 else ''}]"
        print(f"{e['seq']:>6} {rel_ms:>8.1f}m {dur_ms:>9.3f} "
              f"{e['mesh'] if e['mesh'] is not None else '-':>4} "
              f"{e['node'] if e['node'] is not None else '-':>5} "
              f"{e['kind']:<7} {e['outcome']:<10} {e['name']}{slots}")
    bad = [e for e in events if e["outcome"] != "ok"]
    if bad:
        print(f"\n{len(bad)} non-ok event(s):")
        per = collections.Counter(e["outcome"] for e in bad)
        for outcome, n in per.most_common():
            print(f"  {outcome:<24} x{n}")
        last = bad[-1]
        print(f"  last: seq {last['seq']} {last['kind']} "
              f"{last['name']} -> {last['outcome']}")
    else:
        print("\nall events ok")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    pr = sub.add_parser("record", help="run CMD traced, merge its traces")
    pr.add_argument("out")
    pr.add_argument("cmd", nargs=argparse.REMAINDER)
    pr.set_defaults(func=cmd_record)

    pm = sub.add_parser("merge", help="merge trace files onto one timeline")
    pm.add_argument("out")
    pm.add_argument("traces", nargs="+")
    pm.set_defaults(func=cmd_merge)

    ps = sub.add_parser("summarize", help="per-category totals + top spans")
    ps.add_argument("trace")
    ps.add_argument("--top", type=int, default=10)
    ps.add_argument("--bubbles", action="store_true",
                    help="run the step perf analyzer (per-mesh bubble "
                         "fractions, critical path)")
    ps.add_argument("--edges", action="store_true",
                    help="per-reshard-edge wire table (strategy, bytes, "
                         "wire us, achieved GB/s) from reshard.wire spans")
    ps.set_defaults(func=cmd_summarize)

    pt = sub.add_parser("top", help="hottest span names")
    pt.add_argument("trace")
    pt.add_argument("--top", type=int, default=20)
    pt.set_defaults(func=cmd_top)

    pf = sub.add_parser("flight",
                        help="pretty-print a flight-recorder dump")
    pf.add_argument("dump")
    pf.add_argument("--last", type=int, default=0,
                    help="show only the last N events (0 = all)")
    pf.set_defaults(func=cmd_flight)

    args = p.parse_args(argv)
    args.func(args)


if __name__ == "__main__":
    main()
