"""Flash-vs-XLA attention at long sequence lengths (fwd+bwd), on-chip.

The regime where blocked attention should win: XLA's reference path
materializes the (B, H, S, S) score tensor in HBM (fp32), so its HBM
traffic grows as S^2 while flash stays O(S * D).  Each case is memory-
estimated first and SKIPPED above the safety gate (the relay wedges on
near-OOM programs — never attempt).  Run under an external timeout:

    timeout 600 python scripts/flash_longseq_bench.py

Prints one JSON line per (impl, seq, blocks) case.
"""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from alpa_tpu.model.gpt_model import reference_attention
from alpa_tpu.ops.flash_attention import flash_attention

SAFE_HBM_GB = 10.0


def est_hbm_gb(impl, b, s, h, d, dtype_bytes=2):
    qkv = 3 * b * s * h * d * dtype_bytes
    grads = qkv + b * s * h * d * dtype_bytes
    if impl == "reference":
        # fp32 S^2 temporaries across fwd+bwd: scores, probs (saved for
        # the backward), dprobs, dscores — ~4 live buffers at peak
        scores = 4 * b * h * s * s * 4
    else:
        scores = b * h * s * 2 * 4  # lse + delta rows
    return (qkv + grads + scores) / 1e9


def run_case(impl, s, b=1, h=8, d=64, block_q=256, block_k=256, n_iter=10):
    est = est_hbm_gb(impl, b, s, h, d)
    if est > SAFE_HBM_GB:
        print(json.dumps({"impl": impl, "seq": s,
                          "skipped": f"est {est:.1f} GB > {SAFE_HBM_GB}"}),
              flush=True)
        return
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, s, h, d), jnp.bfloat16) * 0.5
               for kk in ks)

    if impl == "reference":
        attn = lambda q, k, v: reference_attention(q, k, v, causal=True)
    else:
        attn = lambda q, k, v: flash_attention(
            q, k, v, causal=True, block_q=block_q, block_k=block_k)

    def loss(q, k, v):
        return (attn(q, k, v).astype(jnp.float32) ** 2).sum()

    step = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    g = step(q, k, v)
    float(g[0][0, 0, 0, 0])  # compile + settle
    tic = time.perf_counter()
    for _ in range(n_iter):
        g = step(q, k, v)
    float(g[0][0, 0, 0, 0])
    lat = (time.perf_counter() - tic) / n_iter
    # causal fwd: qk + pv = 2 * 2*b*h*s^2*d * 0.5; bwd ~2.5x fwd
    flops = 3.5 * 2 * b * h * s * s * d
    print(json.dumps({
        "impl": impl, "seq": s, "batch": b, "heads": h,
        "blocks": [block_q, block_k] if impl == "flash" else None,
        "latency_s": round(lat, 5),
        "tflops": round(flops / lat / 1e12, 2),
        "est_hbm_gb": round(est, 2),
    }), flush=True)


def main():
    import sys
    which = sys.argv[1] if len(sys.argv) > 1 else "compare"
    if which == "compare":
        for s in (2048, 4096):
            run_case("reference", s)
            run_case("flash", s)
    elif which == "blocks":
        for bq, bk in ((128, 128), (256, 256), (512, 512), (256, 512),
                       (512, 1024)):
            run_case("flash", 4096, block_q=bq, block_k=bk)
    elif which == "long":
        # flash-only: XLA's S^2 scores no longer fit here
        for s in (8192, 16384):
            run_case("flash", s)
            run_case("reference", s)  # will skip via the gate at 16k


if __name__ == "__main__":
    main()
