"""Driver dispatch-overhead microbench (SURVEY §7 hard part #5).

The round-4 artifact (dispatch_latency.json, per_inst_us ~9.6ms) timed
RUN *compute*, not dispatch: its payloads were real training matmuls.
Here the payloads are near-zero-FLOP (hidden dim 8), so the instruction
loop's wall time IS the driver cost — Python stream interpretation +
jitted-call enqueue — at 8 single-device meshes.  On an async backend
RUN returns at enqueue, so per-instruction wall time bounds per-tick
dispatch.  Since ISSUE 2 the default ("auto") mode replays the register
-file lowering; pass ``dispatch_mode`` to measure a specific mode, or
use benchmark/bench_dispatch.py for the full mode comparison.

Writes benchmark/results/dispatch_overhead.json; the sub-ms assertion
lives in tests/runtime/test_dispatch_overhead.py.
"""
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def measure(n_steps=10, dispatch_mode=None):
    import alpa_tpu
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)
    from alpa_tpu.testing import (create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    alpa_tpu.init(cluster="local")
    prev_mode = global_config.pipeline_dispatch_mode
    if dispatch_mode is not None:
        global_config.pipeline_dispatch_mode = dispatch_mode
    try:
        state, batch = create_mlp_train_state_and_batch(
            batch_size=8, input_dim=8, hidden_dim=8, output_dim=8,
            num_layers=8)
        method = PipeshardParallel(
            num_micro_batches=2,
            layer_option=AutoLayerOption(layer_num=8),
            stage_option=UniformStageOption(num_stages=8))
        step = get_mlp_train_step(method, use_value_and_grad=True)

        state, loss = step(state, batch)       # compile
        float(loss)
        ex = step.get_last_executable()

        best = None
        for _ in range(n_steps):
            state, loss = step(state, batch)
            float(loss)                        # drain before reading stats
            st = dict(ex.last_dispatch_stats)
            if best is None or st["per_inst_us"] < best["per_inst_us"]:
                best = st
        best["n_meshes"] = ex.num_meshes
        best["payload"] = "mlp h8 x 8 layers, bs8, 2 microbatches "\
            "(near-zero FLOPs: wall time is driver dispatch, not compute)"
        return best
    finally:
        global_config.pipeline_dispatch_mode = prev_mode


def main():
    from alpa_tpu.platform import pin_cpu_platform
    pin_cpu_platform(8)
    stats = measure()
    out = os.path.join(REPO, "benchmark", "results",
                       "dispatch_overhead.json")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w", encoding="utf-8") as f:
        json.dump(stats, f, indent=1)
    print(json.dumps(stats))


if __name__ == "__main__":
    main()
