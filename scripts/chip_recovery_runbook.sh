#!/bin/bash
# Chip-recovery runbook: the exact measurement sequence to run when the
# axon relay clears, most-valuable-first, each leg gated and guarded.
#
# Discipline (see bench.py header): every leg runs in a child process
# with a hard timeout; a probe runs BETWEEN legs and the runbook STOPS
# at the first wedge sign so one bad leg cannot take the rest down; all
# configs pre-validated against the HBM estimator (the relay wedges on
# near-OOM programs and stays wedged for hours).
#
# This probe-between-legs discipline is codified in
# alpa_tpu/elastic.py (WedgeDetector: ok / wedged / dead, stop at the
# first wedge); training runs recover automatically through the
# ElasticSupervisor.  When recovering a run by hand, restore the step
#   python scripts/ckpt_tool.py last-good "$CKPT_ROOT"
# prints — the same hash-verified step the supervisor rolls back to
# (docs/fault_tolerance.md#elastic-training).
#
#   bash scripts/chip_recovery_runbook.sh [results_file]
#
# Legs (in order):
#   1. known-good bench (h2048-l16 bs8, the official number) — FIRST,
#      so whatever happens later the round has a recorded result
#   2. bf16 adam moment variant (est 13.8 GB < gate)
#   3. h2048-l24 + bf16adam + chunked CE (est 14.7 GB < gate)
#   4. flash-vs-XLA longseq compare (attention-only, est << gate)
#   5. flash block-size sweep at seq 4096
set -u -o pipefail
cd "$(dirname "$0")/.."
OUT="${1:-benchmark/results/recovery_run.jsonl}"
mkdir -p "$(dirname "$OUT")"

probe() {
    timeout 120 python bench.py --probe
}

leg() {
    local name="$1"; shift
    echo "=== leg: $name" | tee -a "$OUT"
    if ! probe; then
        echo "{\"leg\": \"$name\", \"skipped\": \"probe failed - stopping\"}" \
            | tee -a "$OUT"
        exit 1
    fi
    # a failed leg is RECORDED (not mistaken for success) and the
    # runbook continues — the next leg's probe decides whether the
    # chip is still usable.  Guard on the LEG's status, not the
    # pipeline's (a tee failure must not forge a failed_rc: 0 record).
    "$@" 2>>"$OUT.err" | tee -a "$OUT"
    local rc=${PIPESTATUS[0]}
    if [ "$rc" -ne 0 ]; then
        echo "{\"leg\": \"$name\", \"failed_rc\": $rc}" | tee -a "$OUT"
    fi
}

leg known-good       timeout 600 python bench.py --self-timeout 540
leg bf16adam         env ALPA_TPU_BENCH_OPT=bf16adam \
                     timeout 600 python bench.py --self-timeout 540
leg h2048l24-lean    env ALPA_TPU_BENCH_OPT=bf16adam \
                         ALPA_TPU_BENCH_CE=chunked \
                         ALPA_TPU_BENCH_SHAPE=h2048l24 \
                     timeout 700 python bench.py --self-timeout 640
leg flash-compare    timeout 600 python scripts/flash_longseq_bench.py compare
leg flash-blocks     timeout 600 python scripts/flash_longseq_bench.py blocks
#   6. HBM-estimator validation: estimate_hbm_gb vs measured
#      peak_bytes_in_use per gated rung (VERDICT r4 next #8) — its own
#      probe-between-rungs discipline inside
leg hbm-check        timeout 1800 python scripts/hbm_estimator_check.py
#   7. MFU breakdown: nested sub-program timings attribute step time to
#      forward / lm-head+CE / backward / optimizer vs a pure-matmul
#      ceiling (VERDICT r4 next #2's profile-backed breakdown).  Budget
#      covers the script's internal worst case (5 x (probe + 600 s
#      child)); the script also flushes its JSON after every leg.
leg mfu-breakdown    timeout 4200 python scripts/mfu_breakdown.py
echo "=== runbook complete" | tee -a "$OUT"
