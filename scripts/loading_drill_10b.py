"""10B-class disk-sharded loading drill (VERDICT r4 next #10).

Proves the 175B-class loading path beyond unit scale, end to end on CPU:

  1. synthesize a ~10B-parameter GPT checkpoint on disk leaf-by-leaf
     (O(largest leaf) RAM; ref the numpy-per-parameter layout of
     load_opt_params_worker_func, opt_model.py:865);
  2. load it with ``load_params_dir`` into tp=8-sharded arrays on the
     virtual CPU mesh — memmap slice reads only (ref
     load_params_dis_array, opt_model.py:956) — and run a jit forward;
  3. run the SAME memmapped weights through a 4-stage pipeshard
     INFERENCE executable (placement by the executable, one stage per
     submesh);
  4. verify both logits against an independent streamed layer-by-layer
     reference that reads one layer's weights at a time (peak RAM one
     layer) — three independent consumers of one checkpoint agreeing.

Writes benchmark/results/loading_drill_10b.json.  ``--small`` runs the
same wiring at toy scale (the regression test's mode).
"""
import argparse
import json
import os
import resource
import shutil
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_shardings(params_aval, mesh):
    """tp shardings: 2D weights split on their largest axis, embeddings
    on the vocab axis, 1D leaves replicated."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    def leaf_sharding(path, leaf):
        shape = leaf.shape
        if len(shape) < 2:
            return NamedSharding(mesh, P())
        axis = int(np.argmax(shape))
        if shape[axis] % mesh.size != 0:
            return NamedSharding(mesh, P())
        spec = [None] * len(shape)
        spec[axis] = "tp"
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map_with_path(leaf_sharding, params_aval)


def streamed_reference(path, cfg, ids):
    """Layer-by-layer forward reading one leaf at a time from disk —
    an independent implementation sharing NO code with GPTModel."""
    import jax
    import jax.numpy as jnp

    def w(name):
        return np.load(os.path.join(path, name + ".npy"), mmap_mode="r")

    def ln(x, prefix):
        mu = x.mean(-1, keepdims=True)
        var = ((x - mu) ** 2).mean(-1, keepdims=True)
        y = (x - mu) / np.sqrt(var + cfg.layer_norm_eps)
        return y * w(prefix + ".scale") + w(prefix + ".bias")

    b, s = ids.shape
    x = np.asarray(w("params.wte.embedding")[ids.reshape(-1)]) \
        .reshape(b, s, -1).astype(np.float32)
    x = x + np.asarray(w("params.wpe.embedding")[np.arange(s)])

    nh = cfg.num_heads
    hd = cfg.hidden_size // nh
    causal = np.tril(np.ones((s, s), bool))
    for i in range(cfg.num_layers):
        pf = f"params.h{i}."
        a = ln(x, pf + "ln1")
        qkv = a @ w(pf + "attn.qkv.kernel") + w(pf + "attn.qkv.bias")
        q, k, v = np.split(qkv, 3, axis=-1)

        def heads(t):
            return t.reshape(b, s, nh, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        scores = (q @ k.transpose(0, 1, 3, 2)) / np.sqrt(hd)
        scores = np.where(causal, scores, -1e9)
        scores = scores - scores.max(-1, keepdims=True)
        probs = np.exp(scores)
        probs = probs / probs.sum(-1, keepdims=True)
        o = (probs @ v).transpose(0, 2, 1, 3).reshape(b, s, -1)
        x = x + (o @ w(pf + "attn.out.kernel") + w(pf + "attn.out.bias"))
        m = ln(x, pf + "ln2")
        h = m @ w(pf + "mlp.fc_in.kernel") + w(pf + "mlp.fc_in.bias")
        h = np.asarray(jax.nn.gelu(jnp.asarray(h), approximate=True))
        x = x + (h @ w(pf + "mlp.fc_out.kernel") + w(pf + "mlp.fc_out.bias"))
    x = ln(x, "params.ln_f")
    if cfg.tie_embeddings:
        logits = x @ np.asarray(w("params.wte.embedding")).T
    else:
        logits = x @ w("params.lm_head.kernel")
    return logits


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--small", action="store_true",
                    help="toy scale (regression-test mode)")
    ap.add_argument("--dir", default="/tmp/loading_drill")
    ap.add_argument("--keep", action="store_true")
    ap.add_argument("--skip-pipeshard", action="store_true")
    ap.add_argument("--commit-artifact", action="store_true",
                    help="write the report into benchmark/results/ "
                    "(the committed artifact); otherwise it lands "
                    "under --dir so test runs never dirty the tree "
                    "with host-dependent timings")
    args = ap.parse_args()

    from alpa_tpu.platform import pin_cpu_platform
    pin_cpu_platform(8)
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    import alpa_tpu
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
    from alpa_tpu.model.weight_loading import (load_params_dir,
                                               synthesize_params_dir)
    from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)

    if args.small:
        cfg = GPTConfig(hidden_size=64, num_layers=4, num_heads=4,
                        seq_len=16, vocab_size=256, dtype=jnp.float32)
    else:
        # ~10.0B params: 50 x (12*4096^2 + 13*4096) + (51200+16)*4096
        cfg = GPTConfig(hidden_size=4096, num_layers=50, num_heads=32,
                        seq_len=16, vocab_size=51200, dtype=jnp.float32)
    model = GPTModel(cfg)
    rng = jax.random.PRNGKey(0)
    ids = np.array([[11, 42, 7, 3, 9, 100, 5, 1]], np.int32)
    ids_aval = jax.ShapeDtypeStruct((1, cfg.seq_len), jnp.int32)
    params_aval = jax.eval_shape(model.init, rng, ids_aval)
    n_params = sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(params_aval))
    report = {"mode": "small" if args.small else "10B",
              "config": {"hidden": cfg.hidden_size,
                         "layers": cfg.num_layers,
                         "vocab": cfg.vocab_size},
              "n_params": n_params}
    print(json.dumps({"n_params": n_params}), flush=True)

    ckpt = os.path.join(args.dir, report["mode"])
    tic = time.time()
    synthesize_params_dir(params_aval, ckpt)
    report["synthesize_s"] = round(time.time() - tic, 1)
    report["disk_gb"] = round(sum(
        os.path.getsize(os.path.join(ckpt, f))
        for f in os.listdir(ckpt)) / 1e9, 2)
    print(json.dumps({"synth_s": report["synthesize_s"],
                      "disk_gb": report["disk_gb"]}), flush=True)

    # ---- streamed single-layer-at-a-time reference ----
    tic = time.time()
    ref = streamed_reference(ckpt, cfg, ids)
    report["streamed_ref_s"] = round(time.time() - tic, 1)
    print(json.dumps({"streamed_ref_s": report["streamed_ref_s"]}),
          flush=True)

    # ---- tp=8 disk-sharded load + jit forward ----
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("tp",))
    shardings = build_shardings(params_aval, mesh)
    tic = time.time()
    params = load_params_dir(ckpt, shardings)
    jax.block_until_ready(jax.tree_util.tree_leaves(params)[0])
    report["sharded_load_s"] = round(time.time() - tic, 1)
    tic = time.time()
    fwd = jax.jit(lambda p, i: model.apply(p, i))
    logits = np.asarray(fwd(params, jnp.asarray(
        np.pad(ids, ((0, 0), (0, cfg.seq_len - ids.shape[1]))))))
    report["tp8_forward_s"] = round(time.time() - tic, 1)
    diff = float(np.max(np.abs(
        logits[:, :ids.shape[1]] - ref)))
    scale = float(np.max(np.abs(ref)) + 1e-9)
    report["tp8_max_abs_diff"] = diff
    report["tp8_rel_diff"] = round(diff / scale, 8)
    assert diff / scale < 1e-3, (diff, scale)
    print(json.dumps({"tp8_ok": True, "rel_diff": report["tp8_rel_diff"],
                      "load_s": report["sharded_load_s"]}), flush=True)
    del params, logits

    # ---- pipeshard inference executable over memmapped leaves ----
    if not args.skip_pipeshard:
        from alpa_tpu.model.weight_loading import _leaf_name
        flat, treedef = jax.tree_util.tree_flatten_with_path(params_aval)
        mm = [np.load(os.path.join(ckpt, _leaf_name(p) + ".npy"),
                      mmap_mode="r") for p, _ in flat]
        params_mm = jax.tree_util.tree_unflatten(treedef, mm)
        alpa_tpu.init(cluster="local")

        @alpa_tpu.parallelize(method=PipeshardParallel(
            num_micro_batches=1,
            layer_option=AutoLayerOption(layer_num=4),
            stage_option=UniformStageOption(num_stages=4),
            pipeline_schedule="inference"), batch_argnums=(1,))
        def forward(p, batch):
            return model.apply(p, batch["ids"])

        batch = {"ids": jnp.asarray(
            np.pad(ids, ((0, 0), (0, cfg.seq_len - ids.shape[1]))))}
        tic = time.time()
        out = np.asarray(forward(params_mm, batch))
        report["pipeshard_first_call_s"] = round(time.time() - tic, 1)
        pdiff = float(np.max(np.abs(out[:, :ids.shape[1]] - ref)))
        report["pipeshard_max_abs_diff"] = pdiff
        report["pipeshard_rel_diff"] = round(pdiff / scale, 8)
        assert pdiff / scale < 1e-3, (pdiff, scale)
        print(json.dumps({"pipeshard_ok": True,
                          "rel_diff": report["pipeshard_rel_diff"]}),
              flush=True)

    report["peak_rss_gb"] = round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6, 2)
    if not args.keep:
        shutil.rmtree(ckpt, ignore_errors=True)

    base = os.path.join(REPO, "benchmark", "results") \
        if args.commit_artifact else args.dir
    os.makedirs(base, exist_ok=True)
    out_path = os.path.join(
        base, "loading_drill_10b_small.json" if args.small
        else "loading_drill_10b.json")
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report), flush=True)


if __name__ == "__main__":
    main()
