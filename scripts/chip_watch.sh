#!/bin/bash
# Probe the chip every 10 min; the moment it answers, run the recovery
# runbook (which banks the known-good bench FIRST).  Log everything.
cd "$(dirname "$0")/.."
LOG=benchmark/results/chip_watch.log
mkdir -p benchmark/results
while true; do
    echo "[$(date -u +%H:%M:%S)] probe" >> "$LOG"
    if timeout 120 python bench.py --probe >> "$LOG" 2>&1; then
        echo "[$(date -u +%H:%M:%S)] CHIP ALIVE - running runbook" >> "$LOG"
        bash scripts/chip_recovery_runbook.sh >> "$LOG" 2>&1
        echo "[$(date -u +%H:%M:%S)] runbook done rc=$?" >> "$LOG"
        exit 0
    fi
    echo "[$(date -u +%H:%M:%S)] wedged; sleeping 600s" >> "$LOG"
    sleep 600
done
