"""Validate bench.py's HBM estimator against measured device memory
(VERDICT r4 next #8).  The relay-wedge gate rides on estimate_hbm_gb;
this compares it with the chip's own peak_bytes_in_use for each gated
shape rung, SMALLEST first with a probe between rungs so a bad rung
cannot take the rest down.

Run on the real chip (no arguments).  Each rung runs in a CHILD process
with a hard timeout (wedge isolation); the child does 2 train steps and
prints the measured stats.  Results append to
benchmark/results/hbm_estimator_check.jsonl.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# (shape, opt_variant, chunked_ce) — the bench's gated rungs, smallest
# first.  Estimates per bench.py HBM accounting; all below the 16 GB
# gate by construction.
RUNGS = [
    ("h1024l8", "adam", False),
    ("h2048l16", "adam", False),       # the known-good official config
    ("h2048l16", "bf16adam", False),
    ("h2048l24", "bf16adam", True),
]

SHAPES = {"h1024l8": (1024, 8), "h2048l16": (2048, 16),
          "h2048l24": (2048, 24)}

_CHILD_SRC = r'''
import json, sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp, optax
from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
from alpa_tpu.model.model_util import gpt_lm_loss
from bench import estimate_hbm_gb

hidden, layers, opt_variant, chunked = {hidden}, {layers}, {opt!r}, {chunked}
config = GPTConfig(hidden_size=hidden, num_layers=layers,
                   num_heads=hidden // 64, seq_len=1024, vocab_size=51200,
                   dtype=jnp.bfloat16, attention_impl="reference",
                   remat_blocks=True)
batch_size = 8
est = estimate_hbm_gb(config, batch_size,
                      optimizer_bytes_per_param=6.0 if opt_variant ==
                      "bf16adam" else 8.0, chunked_ce=chunked)
model = GPTModel(config)
rng = jax.random.PRNGKey(0)
ids = jnp.zeros((batch_size, config.seq_len), jnp.int32)
params = model.init(rng, ids)
if opt_variant == "bf16adam":
    tx = optax.adam(1e-4, mu_dtype=jnp.bfloat16)
else:
    tx = optax.adam(1e-4)
opt_state = tx.init(params)
batch = dict(input_ids=ids, labels=ids)

def loss_fn(p):
    return gpt_lm_loss(model.apply, p, batch, chunked=chunked)

@jax.jit
def step(params, opt_state, batch):
    loss, grads = jax.value_and_grad(loss_fn)(params)
    updates, opt_state = tx.update(grads, opt_state, params)
    return optax.apply_updates(params, updates), opt_state, loss

for _ in range(2):
    params, opt_state, loss = step(params, opt_state, batch)
    float(loss)  # scalar D2H readback = the only real relay fence
d = jax.devices()[0]
stats = d.memory_stats() or {{}}
print(json.dumps({{
    "est_gb": round(est, 2),
    "peak_gb": round(stats.get("peak_bytes_in_use", 0) / 1e9, 2),
    "in_use_gb": round(stats.get("bytes_in_use", 0) / 1e9, 2),
    "limit_gb": round(stats.get("bytes_limit", 0) / 1e9, 2),
    "raw_keys": sorted(stats)[:12],
}}))
'''


def probe():
    return subprocess.run([sys.executable,
                           os.path.join(REPO, "bench.py"), "--probe"],
                          timeout=150).returncode == 0


def main():
    out_path = os.path.join(REPO, "benchmark", "results",
                            "hbm_estimator_check.jsonl")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    for shape, opt, chunked in RUNGS:
        if not probe():
            rec = {"rung": shape, "opt": opt,
                   "skipped": "probe failed - stopping"}
            print(json.dumps(rec), flush=True)
            with open(out_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(rec) + "\n")
            return 1
        hidden, layers = SHAPES[shape]
        src = _CHILD_SRC.format(repo=REPO, hidden=hidden, layers=layers,
                                opt=opt, chunked=chunked)
        tic = time.time()
        try:
            proc = subprocess.run([sys.executable, "-c", src],
                                  capture_output=True, text=True,
                                  timeout=600)
            line = proc.stdout.strip().splitlines()[-1] if \
                proc.stdout.strip() else "{}"
            try:
                payload = json.loads(line)
            except json.JSONDecodeError:
                # non-JSON child output (crash mid-print, warning) must
                # record a failure, not abort the remaining rungs
                payload = {"bad_stdout_tail": proc.stdout[-200:]}
            rec = {"rung": shape, "opt": opt, "chunked_ce": chunked,
                   "wall_s": round(time.time() - tic, 1), **payload}
            if proc.returncode != 0:
                rec["rc"] = proc.returncode
                rec["stderr_tail"] = proc.stderr[-400:]
        except subprocess.TimeoutExpired:
            rec = {"rung": shape, "opt": opt, "timeout": True,
                   "wall_s": round(time.time() - tic, 1)}
        if "peak_gb" in rec and rec.get("peak_gb"):
            rec["est_over_measured"] = round(
                rec["est_gb"] / max(rec["peak_gb"], 1e-9), 3)
        print(json.dumps(rec), flush=True)
        with open(out_path, "a", encoding="utf-8") as f:
            f.write(json.dumps(rec) + "\n")
        if rec.get("timeout"):
            print(json.dumps({"stopping": "rung timed out (wedge risk)"}),
                  flush=True)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
