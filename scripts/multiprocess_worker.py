"""Worker for the multi-process jax.distributed CPU tests.

Each process pins a virtual CPU backend, joins the coordinator, and
drives alpa_tpu over the resulting global mesh — proving the
single-controller design survives process boundaries (VERDICT r1
next#6; analog of the reference's Ray-emulated multi-host tests,
ref tests/pipeline_parallel/ + alpa/device_mesh.py:979).

Run (same on all):
  python multiprocess_worker.py <process_id> <nproc> <port> [mode]

mode "basic" (default, 4 devices/proc): ShardParallel + 2-stage uniform
pipeshard with serial oracles.
mode "auto" (2 devices/proc, meant for 4 processes): AUTO stage
construction (OSDI'22 DP), planned/tiled cross-process resharding
(packed-tile collective, not full-array gather), and a measured
per-instruction dispatch latency (SURVEY §7 hard part 5), printed as
``dispatch_stats {...}``.

Prints ``MP_OK <process_id>`` on success.
"""
import json
import os
import sys


def _auto_mode(nproc, process_id):
    """4-process proof: auto stage construction + planned (packed-tile)
    cross-process resharding + dispatch-latency measurement."""
    import time

    import jax

    import alpa_tpu
    import alpa_tpu.distributed as dist
    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel.layer_construction import (
        ManualLayerOption)
    from alpa_tpu.pipeline_parallel.stage_construction import AutoStageOption
    from alpa_tpu.testing import (assert_allclose,
                                  create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    alpa_tpu.init(cluster="distributed")
    # cross-process RESHARD instructions drive the tile plan via the
    # packed-tile collective instead of a full-array host gather
    global_config.resharding_execution = "planned"

    method = alpa_tpu.PipeshardParallel(
        num_micro_batches=2,
        layer_option=ManualLayerOption(),
        stage_option=AutoStageOption())
    state_p, batch = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    state_s, _ = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    pstep = get_mlp_train_step(method, use_value_and_grad=True)
    serial = get_mlp_train_step(None)

    state_p, loss_p = pstep(state_p, batch)
    state_s, loss_s = serial(state_s, batch)
    lp = float(dist.host_gather(loss_p))
    assert_allclose(float(loss_s), lp, 2e-3, 2e-3)
    params_p = jax.tree_util.tree_map(dist.host_gather, state_p.params)
    assert_allclose(jax.device_get(state_s.params), params_p, 2e-3, 2e-3)

    ex = pstep.get_last_executable()
    n_meshes = ex.num_meshes
    print(f"auto pipeshard ok: loss {lp:.6f} meshes {n_meshes}", flush=True)

    # steady-state dispatch latency: time a few steps after warmup and
    # report the Python-loop overhead per instruction
    for _ in range(2):
        state_p, loss_p = pstep(state_p, batch)
    tic = time.perf_counter()
    n_iter = 3
    for _ in range(n_iter):
        state_p, loss_p = pstep(state_p, batch)
    dist.host_gather(loss_p)
    step_s = (time.perf_counter() - tic) / n_iter
    stats = dict(ex.last_dispatch_stats)
    stats["step_s"] = step_s
    stats["executed_cross_mesh_bytes"] = ex._executed_resharding_bytes
    print("dispatch_stats " + json.dumps(stats), flush=True)
    if n_meshes > 1:
        assert ex._executed_resharding_bytes > 0, \
            "multi-mesh step must move cross-mesh bytes"

    # ---- 4 uniform stages: one stage mesh PER PROCESS (the pod-dispatch
    # shape), cross-process boundaries driven by the packed-tile plan ----
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)

    from alpa_tpu.pipeline_parallel.layer_construction import AutoLayerOption

    method4 = alpa_tpu.PipeshardParallel(
        num_micro_batches=2,
        layer_option=AutoLayerOption(layer_num=nproc),
        stage_option=UniformStageOption(num_stages=nproc))
    state_4, _ = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    state_4s, _ = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    pstep4 = get_mlp_train_step(method4, use_value_and_grad=True)
    state_4, loss_4 = pstep4(state_4, batch)
    state_4s, loss_4s = serial(state_4s, batch)
    l4 = float(dist.host_gather(loss_4))
    assert_allclose(float(loss_4s), l4, 2e-3, 2e-3)
    ex4 = pstep4.get_last_executable()
    assert ex4.num_meshes == nproc, ex4.num_meshes
    st4 = dict(ex4.last_dispatch_stats)
    st4["executed_cross_mesh_bytes"] = ex4._executed_resharding_bytes
    assert st4["by_opcode"]["RESHARD"]["n"] > 0, st4
    assert ex4._executed_resharding_bytes > 0, \
        "per-process stages must move cross-mesh bytes"
    print("dispatch_stats4 " + json.dumps(st4), flush=True)
    print(f"uniform4 ok: loss {l4:.6f} meshes {ex4.num_meshes}", flush=True)

    dist.sync_global_devices("done")
    print(f"MP_OK {process_id}", flush=True)


def main():
    process_id = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]
    mode = sys.argv[4] if len(sys.argv) > 4 else "basic"

    from alpa_tpu.platform import set_cpu_device_count
    set_cpu_device_count(2 if mode == "auto" else 4)
    import jax
    import alpa_tpu.distributed as dist
    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=nproc, process_id=process_id)
    ndev_local = 2 if mode == "auto" else 4
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == ndev_local * nproc, jax.devices()
    assert jax.local_device_count() == ndev_local

    if mode == "auto":
        _auto_mode(nproc, process_id)
        return

    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax.training import train_state

    import alpa_tpu
    from alpa_tpu.testing import (MLPModel, assert_allclose,
                                  create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    alpa_tpu.init(cluster="distributed")

    # ---- ShardParallel across the global 8-device mesh ----
    rng = jax.random.PRNGKey(0)
    model = MLPModel(hidden_dim=32, output_dim=32, num_layers=2,
                     manual_pipeline_layer=False)
    x = jax.random.normal(rng, (32, 32))
    y = jax.random.normal(jax.random.PRNGKey(1), (32, 32))
    params = model.init(rng, x)
    tx = optax.sgd(0.05)
    state_p = train_state.TrainState.create(apply_fn=model.apply,
                                            params=params, tx=tx)
    state_s = train_state.TrainState.create(apply_fn=model.apply,
                                            params=params, tx=tx)

    @alpa_tpu.parallelize(method=alpa_tpu.ShardParallel(),
                          donate_argnums=())
    def pstep(state, batch):
        def loss_fn(p):
            out = state.apply_fn(p, batch["x"])
            return jnp.mean((out - batch["y"]) ** 2)
        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    @jax.jit
    def sstep(state, batch):
        def loss_fn(p):
            out = state.apply_fn(p, batch["x"])
            return jnp.mean((out - batch["y"]) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    batch = {"x": x, "y": y}
    for _ in range(3):
        state_p, loss_p = pstep(state_p, batch)
        state_s, loss_s = sstep(state_s, batch)
        assert_allclose(float(loss_s), float(loss_p), 2e-3, 2e-3)
    print(f"shard_parallel ok: loss {float(loss_p):.6f}", flush=True)

    # ---- 2-stage pipeshard step, each stage mesh spanning both hosts ----
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.pipeline_parallel.layer_construction import (
        ManualLayerOption)
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)

    method = PipeshardParallel(num_micro_batches=2,
                               layer_option=ManualLayerOption(),
                               stage_option=UniformStageOption(num_stages=2))
    state_pp, pbatch = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    state_ps, _ = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    ppstep = get_mlp_train_step(method, use_value_and_grad=True)
    serial = get_mlp_train_step(None)
    state_pp, loss_pp = ppstep(state_pp, pbatch)
    state_ps, loss_ps = serial(state_ps, pbatch)
    # outputs live on their producing stage's mesh — not all addressable
    # from every process; host_gather reconstructs them everywhere
    lp = float(dist.host_gather(loss_pp))
    assert_allclose(float(loss_ps), lp, 2e-3, 2e-3)
    params_p = jax.tree_util.tree_map(dist.host_gather, state_pp.params)
    assert_allclose(jax.device_get(state_ps.params), params_p, 2e-3, 2e-3)
    print(f"pipeshard ok: loss {lp:.6f}", flush=True)

    dist.sync_global_devices("done")
    print(f"MP_OK {process_id}", flush=True)


if __name__ == "__main__":
    main()
