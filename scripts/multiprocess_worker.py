"""Worker for the 2-process jax.distributed CPU test.

Each process pins a 4-device virtual CPU backend, joins the coordinator,
and drives alpa_tpu over the resulting 8-device global mesh — proving
the single-controller design survives a process boundary (VERDICT r1
next#6; analog of the reference's Ray-emulated multi-host tests,
ref tests/pipeline_parallel/ + alpa/device_mesh.py:979).

Run (same on both):  python multiprocess_worker.py <process_id> <nproc> <port>
Prints ``MP_OK <process_id>`` on success.
"""
import os
import sys


def main():
    process_id = int(sys.argv[1])
    nproc = int(sys.argv[2])
    port = sys.argv[3]

    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", 4)
    import alpa_tpu.distributed as dist
    dist.initialize(coordinator_address=f"127.0.0.1:{port}",
                    num_processes=nproc, process_id=process_id)
    assert jax.process_count() == nproc, jax.process_count()
    assert jax.device_count() == 4 * nproc, jax.devices()
    assert jax.local_device_count() == 4

    import jax.numpy as jnp
    import numpy as np
    import optax
    from flax.training import train_state

    import alpa_tpu
    from alpa_tpu.testing import (MLPModel, assert_allclose,
                                  create_mlp_train_state_and_batch,
                                  get_mlp_train_step)

    alpa_tpu.init(cluster="distributed")

    # ---- ShardParallel across the global 8-device mesh ----
    rng = jax.random.PRNGKey(0)
    model = MLPModel(hidden_dim=32, output_dim=32, num_layers=2,
                     manual_pipeline_layer=False)
    x = jax.random.normal(rng, (32, 32))
    y = jax.random.normal(jax.random.PRNGKey(1), (32, 32))
    params = model.init(rng, x)
    tx = optax.sgd(0.05)
    state_p = train_state.TrainState.create(apply_fn=model.apply,
                                            params=params, tx=tx)
    state_s = train_state.TrainState.create(apply_fn=model.apply,
                                            params=params, tx=tx)

    @alpa_tpu.parallelize(method=alpa_tpu.ShardParallel(),
                          donate_argnums=())
    def pstep(state, batch):
        def loss_fn(p):
            out = state.apply_fn(p, batch["x"])
            return jnp.mean((out - batch["y"]) ** 2)
        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    @jax.jit
    def sstep(state, batch):
        def loss_fn(p):
            out = state.apply_fn(p, batch["x"])
            return jnp.mean((out - batch["y"]) ** 2)
        loss, grads = jax.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    batch = {"x": x, "y": y}
    for _ in range(3):
        state_p, loss_p = pstep(state_p, batch)
        state_s, loss_s = sstep(state_s, batch)
        assert_allclose(float(loss_s), float(loss_p), 2e-3, 2e-3)
    print(f"shard_parallel ok: loss {float(loss_p):.6f}", flush=True)

    # ---- 2-stage pipeshard step, each stage mesh spanning both hosts ----
    from alpa_tpu import PipeshardParallel
    from alpa_tpu.pipeline_parallel.layer_construction import (
        ManualLayerOption)
    from alpa_tpu.pipeline_parallel.stage_construction import (
        UniformStageOption)

    method = PipeshardParallel(num_micro_batches=2,
                               layer_option=ManualLayerOption(),
                               stage_option=UniformStageOption(num_stages=2))
    state_pp, pbatch = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    state_ps, _ = create_mlp_train_state_and_batch(
        batch_size=64, num_layers=4, manual_pipeline_layer=True)
    ppstep = get_mlp_train_step(method, use_value_and_grad=True)
    serial = get_mlp_train_step(None)
    state_pp, loss_pp = ppstep(state_pp, pbatch)
    state_ps, loss_ps = serial(state_ps, pbatch)
    # outputs live on their producing stage's mesh — not all addressable
    # from every process; host_gather reconstructs them everywhere
    lp = float(dist.host_gather(loss_pp))
    assert_allclose(float(loss_ps), lp, 2e-3, 2e-3)
    params_p = jax.tree_util.tree_map(dist.host_gather, state_pp.params)
    assert_allclose(jax.device_get(state_ps.params), params_p, 2e-3, 2e-3)
    print(f"pipeshard ok: loss {lp:.6f}", flush=True)

    dist.sync_global_devices("done")
    print(f"MP_OK {process_id}", flush=True)


if __name__ == "__main__":
    main()
