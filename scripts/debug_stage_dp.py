"""Capture the stage-DP inputs for the recorded auto-plan artifacts and
cross-check the chosen partition (diagnosis harness for the degenerate
[7,1]-style splits; VERDICT r4 next #3)."""
import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from alpa_tpu.platform import pin_cpu_platform  # noqa: E402

pin_cpu_platform(8)

from alpa_tpu.mesh_profiling import (analytic_calibration,  # noqa: E402
                                     set_global_calibration)
import alpa_tpu.pipeline_parallel.stage_dp as sdp  # noqa: E402

captured = {}
orig = sdp.stage_dp_solve


def spy(costs, submesh_sizes, num_devices, num_micro_batches,
        mem_param=None, mem_act=None, mem_budget=0.0, inflight_mode="1f1b"):
    captured["costs"] = np.array(costs)
    captured["sizes"] = list(submesh_sizes)
    captured["D"] = num_devices
    captured["B"] = num_micro_batches
    captured["mem_param"] = None if mem_param is None else np.array(mem_param)
    captured["mem_act"] = None if mem_act is None else np.array(mem_act)
    captured["mem_budget"] = mem_budget
    captured["inflight_mode"] = inflight_mode
    out = orig(costs, submesh_sizes, num_devices, num_micro_batches,
               mem_param, mem_act, mem_budget, inflight_mode)
    captured["part"] = out
    return out


sdp.stage_dp_solve = spy

from benchmark.auto_search_artifact import search_gpt_plan  # noqa: E402

set_global_calibration(analytic_calibration("v5e"))
case = sys.argv[1] if len(sys.argv) > 1 else "2x8"
if case == "2x8":
    plan = search_gpt_plan("6.7B", n_devices=16, num_hosts=2)
elif case == "1x8":
    plan = search_gpt_plan("6.7B", n_devices=8, num_hosts=1)
else:
    raise SystemExit(f"unknown case {case}")

C = captured["costs"]
L, _, M = C.shape
sizes = captured["sizes"]
D, B = captured["D"], captured["B"]
print(json.dumps({"case": case, "L": L, "M": M, "sizes": sizes,
                  "D": D, "B": B,
                  "mem_budget": captured["mem_budget"],
                  "part": captured["part"],
                  "plan": plan["forward_stage_layer_ids"]}))
print("per-layer costs by submesh (diag):")
for m in range(M):
    print(f"  m={m} size={sizes[m]}:",
          [round(float(C[i, i, m]), 4) for i in range(L)])
print("full-span cost by submesh:",
      [round(float(C[0, L - 1, m]), 4) for m in range(M)])
print("additivity check (span vs sum of diag), largest submesh:")
m = int(np.argmax(sizes))
for i in range(L):
    for j in (L - 1,):
        span = C[i, j, m]
        add = sum(C[k, k, m] for k in range(i, j + 1))
        print(f"  C[{i},{j}] = {span:.4f}  sum(diag) = {add:.4f}")
mp, ma = captured["mem_param"], captured["mem_act"]
if mp is not None and captured["mem_budget"]:
    print("memory (largest submesh), per-layer param/act GB:")
    print("  param:", [round(float(mp[i, i, m]) / 1e9, 2) for i in range(L)])
    print("  act:  ", [round(float(ma[i, i, m]) / 1e9, 2) for i in range(L)])
    print("  full-span param:", round(float(mp[0, L - 1, m]) / 1e9, 2),
          "act:", round(float(ma[0, L - 1, m]) / 1e9, 2),
          "budget:", captured["mem_budget"] / 1e9)

np.savez(os.path.join(REPO, "benchmark", "results",
                      f"stage_dp_inputs_{case}.npz"),
         costs=C, sizes=np.array(sizes), D=D, B=B,
         mem_param=mp if mp is not None else np.zeros_like(C),
         mem_act=ma if ma is not None else np.zeros_like(C),
         mem_budget=captured["mem_budget"])
print("saved inputs npz")
