"""On-chip config sweep for the bench model: attention impl x remat x loss.

Prints one JSON line per config.  Stays inside the safe envelope
(batch 8, seq 1024 — the relay wedges above that)."""
import json
import time

import jax
import jax.numpy as jnp
import optax
from flax.training import train_state

import alpa_tpu
from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
from alpa_tpu.model.model_util import gpt_lm_loss
from alpa_tpu.util import compute_gpt_tflops


def run_one(attention_impl, remat, chunked, batch_size=8,
            hidden=768, layers=12, seq_len=1024, remat_policy=None):
    config = GPTConfig(hidden_size=hidden, num_layers=layers,
                      num_heads=hidden // 64,
                      seq_len=seq_len, vocab_size=51200,
                      dtype=jnp.bfloat16, attention_impl=attention_impl,
                      remat_blocks=remat, remat_policy=remat_policy)
    model = GPTModel(config)
    rng = jax.random.PRNGKey(0)
    input_ids = jax.random.randint(rng, (batch_size, config.seq_len), 0,
                                   config.vocab_size)
    labels = jax.random.randint(rng, (batch_size, config.seq_len), 0,
                                config.vocab_size)
    params = model.init(rng, input_ids)
    tx = optax.adam(1e-4)
    state = train_state.TrainState.create(apply_fn=model.apply,
                                          params=params, tx=tx)

    @alpa_tpu.parallelize(method=alpa_tpu.ShardParallel(),
                          donate_argnums=(0,))
    def train_step(state, batch):
        def loss_fn(p):
            return gpt_lm_loss(state.apply_fn, p, batch, chunked=chunked)
        loss, grads = alpa_tpu.value_and_grad(loss_fn)(state.params)
        return state.apply_gradients(grads=grads), loss

    batch = {"input_ids": input_ids, "labels": labels}
    for _ in range(3):
        state, loss = train_step(state, batch)
        float(loss)
    n_iter = 10
    tic = time.perf_counter()
    for _ in range(n_iter):
        state, loss = train_step(state, batch)
    float(loss)
    latency = (time.perf_counter() - tic) / n_iter
    tflops = compute_gpt_tflops(batch_size, config.seq_len,
                                config.num_layers, config.hidden_size,
                                config.vocab_size, 1, latency)
    print(json.dumps({"attn": attention_impl, "remat": remat,
                      "policy": remat_policy,
                      "chunked_ce": chunked, "batch": batch_size,
                      "hidden": hidden, "layers": layers, "seq": seq_len,
                      "latency_s": round(latency, 5),
                      "tflops": round(tflops, 2)}), flush=True)
    del state, params
    return tflops


# (attn, remat, chunked, hidden, layers)
SWEEPS = {
    # impl sweep result (2026-07-29, v5e chip): reference/XLA attention, no
    # remat, dense CE wins at GPT-125M bs8: 66.7 TF vs flash 47.7 / remat 53.9
    "impl": [
        ("reference", False, False, 768, 12),
        ("reference", False, True, 768, 12),
        ("flash", False, True, 768, 12),
        ("reference", True, True, 768, 12),
        ("flash", True, True, 768, 12),
    ],
    # model-size sweep: bigger models amortize overhead -> higher MFU;
    # batch stays at 8 (the relay wedges above that).  Result: monotone
    # rise 60.1 (h1024 l24) -> 70.9 (h1536 l24) -> 75.2 (h2048 l16),
    # all with remat; the h1024 no-remat variant failed remote compile.
    "size": [
        ("reference", False, False, 1024, 24),
        ("reference", True, False, 1024, 24),
        ("reference", True, False, 1536, 24),
        ("reference", True, True, 2048, 16),
    ],
    # second rung: find the peak around GPT-1.3B-class shapes
    "size2": [
        ("reference", True, False, 2048, 16),
        ("reference", True, True, 2048, 24),
        ("reference", True, True, 2560, 16),
    ],
    # remat-policy rung (2026-07-29): "dots" saves matmul outputs.
    # RESULT: h2048 l16 bs8 with "dots" WEDGED the relay (est 14.4 GB:
    # 4.8 GB saved dots + 9.6 GB params/adam > safe envelope) — no case
    # completed.  Keep "dots" for smaller models / bs<=4 only; the bench
    # default stays full-block remat.
    "policy": [
        dict(attention_impl="reference", remat=True, chunked=False,
             hidden=2048, layers=16, remat_policy="dots", batch_size=4),
        dict(attention_impl="reference", remat=False, chunked=False,
             hidden=2048, layers=16, batch_size=4),
        dict(attention_impl="reference", remat=True, chunked=False,
             hidden=2048, layers=16, remat_policy="dots", batch_size=4,
             seq_len=2048),
    ],
}


def main():
    import sys
    alpa_tpu.init(cluster="local")
    configs = SWEEPS[sys.argv[1] if len(sys.argv) > 1 else "impl"]
    for case in configs:
        kw = dict(case) if isinstance(case, dict) else dict(
            zip(("attention_impl", "remat", "chunked", "hidden", "layers"),
                case))
        try:
            run_one(kw.pop("attention_impl"), kw.pop("remat"),
                    kw.pop("chunked"), **kw)
        except Exception as e:  # pylint: disable=broad-except
            print(json.dumps({"case": repr(case),
                              "error": repr(e)[:200]}), flush=True)


if __name__ == "__main__":
    main()
