"""Concurrent serving load test (VERDICT r4 next #5).

Drives N concurrent HTTP clients — mixed SSE streaming and
non-streaming — against the stdlib controller + continuous-batching
engine on a tiny CPU model, and records time-to-first-token
percentiles and aggregate decoded tokens/s.  The point is behavior
UNDER CONCURRENCY: ThreadingHTTPServer thread-per-connection fan-in,
engine decode-tick sharing, batcher coalescing.

Writes benchmark/results/serving_load.json when run as a script; the
assertions live in tests/serve/test_serving_load.py.
"""
import http.client
import json
import os
import sys
import threading
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_server(seq_len=128, max_new_tokens=8):
    from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
    from alpa_tpu.serve.controller import Controller, ControllerServer
    from alpa_tpu.serve.generation import Generator

    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                    seq_len=seq_len, vocab_size=64)
    model, params = init_gpt_real(cfg, 1)
    gen = Generator(model, params, cfg, prompt_buckets=[16])
    controller = Controller()
    controller.register_model("tiny", gen)
    server = ControllerServer(controller, "127.0.0.1", 0)
    server.start()
    return server, max_new_tokens


def _one_client(port, i, max_new_tokens, results, n_requests):
    rng = np.random.RandomState(i)
    recs = []
    for _ in range(n_requests):
        prompt = rng.randint(0, 64, (int(rng.randint(3, 12)),)).tolist()
        body = {"model": "tiny", "prompt_ids": prompt,
                "max_new_tokens": max_new_tokens}
        stream = i % 2 == 0
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        t0 = time.perf_counter()
        try:
            if stream:
                body["stream"] = True
                conn.request("POST", "/completions", json.dumps(body),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200, resp.status
                ttft, ntok = None, 0
                while True:
                    line = resp.fp.readline()
                    if not line:
                        break
                    line = line.strip()
                    if not line.startswith(b"data: "):
                        continue
                    evt = json.loads(line[len(b"data: "):])
                    if "token" in evt:
                        ntok += 1
                        if ttft is None:
                            ttft = time.perf_counter() - t0
                    elif "error" in evt:
                        raise RuntimeError(evt["error"])
                    else:
                        break  # done
                recs.append({"mode": "sse", "ttft_s": ttft,
                             "tokens": ntok,
                             "total_s": time.perf_counter() - t0})
            else:
                conn.request("POST", "/completions", json.dumps(body),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200, resp.status
                out = json.loads(resp.read())
                ntok = len(out["output_ids"][0]) - len(prompt)
                dt = time.perf_counter() - t0
                # non-streaming TTFT == full latency (tokens arrive at
                # once); recorded separately so the SSE percentile is
                # not polluted
                recs.append({"mode": "batch", "ttft_s": dt,
                             "tokens": ntok, "total_s": dt})
        except Exception as e:  # pylint: disable=broad-except
            recs.append({"mode": "sse" if stream else "batch",
                         "error": f"{type(e).__name__}: {e}"})
        finally:
            conn.close()
    results[i] = recs


def run_load(n_clients=16, n_requests=3, max_new_tokens=8):
    server, mnt = build_server(max_new_tokens=max_new_tokens)
    port = server.port
    try:
        # warmup: compile the engine decode/prefill + batcher paths once
        # so the percentiles measure steady-state serving, not XLA
        warm = [None, None]
        wt = [threading.Thread(target=_one_client,
                               args=(port, i, mnt, warm, 1))
              for i in range(2)]
        for t in wt:
            t.start()
        for t in wt:
            t.join()
        assert all("error" not in r for recs in warm for r in recs), warm

        results = [None] * n_clients
        tic = time.perf_counter()
        threads = [threading.Thread(target=_one_client,
                                    args=(port, i, mnt, results,
                                          n_requests))
                   for i in range(n_clients)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - tic
    finally:
        server.shutdown()

    flat = [r for recs in results for r in recs]
    errors = [r for r in flat if "error" in r]
    ok = [r for r in flat if "error" not in r]
    sse_ttft = sorted(r["ttft_s"] for r in ok
                      if r["mode"] == "sse" and r["ttft_s"] is not None)
    batch_lat = sorted(r["total_s"] for r in ok if r["mode"] == "batch")

    def pct(xs, p):
        if not xs:
            return None
        return round(xs[min(len(xs) - 1, int(p / 100 * len(xs)))], 4)

    total_tokens = sum(r["tokens"] for r in ok)
    return {
        "n_clients": n_clients,
        "n_requests_per_client": n_requests,
        "max_new_tokens": max_new_tokens,
        "wall_s": round(wall, 3),
        "ok": len(ok),
        "errors": [r["error"] for r in errors],
        "sse_ttft_p50_s": pct(sse_ttft, 50),
        "sse_ttft_p99_s": pct(sse_ttft, 99),
        "batch_latency_p50_s": pct(batch_lat, 50),
        "batch_latency_p99_s": pct(batch_lat, 99),
        "aggregate_tokens_per_s": round(total_tokens / wall, 1),
        "sum_of_individual_s": round(sum(r["total_s"] for r in ok), 3),
    }


def main():
    from alpa_tpu.platform import pin_cpu_platform
    pin_cpu_platform(8)
    stats = run_load()
    out = os.path.join(REPO, "benchmark", "results", "serving_load.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(stats, f, indent=1)
    print(json.dumps(stats))


if __name__ == "__main__":
    main()
