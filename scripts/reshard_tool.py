"""Inspect the collective resharding planner (ISSUE 7).

Usage::

    python scripts/reshard_tool.py plan --shape 1024,1024 \
        --src-devices 4 --dst-devices 4 \
        --src-spec x,None --dst-spec None,None \
        [--dtype float32] [--latency-ms 2.0] [--bandwidth 0] \
        [--wire-model link]

``plan`` plans one cross-mesh edge with :func:`plan_resharding` and
prints the chosen strategy, every candidate's estimated cost and
busiest-link load, and the planned wire bytes — the same per-edge
decision `dump_debug_info` records as ``resharding_plan.txt``.

Spec syntax: comma-separated PartitionSpec entries over the 1-D device
axis ``x`` (``x`` = sharded on that dim, ``None`` = replicated), e.g.
``x,None`` is a row shard.  Runs on the CPU backend with emulated
devices; the planner's tiling math is device-count-driven, so the
decisions match what the real meshes would get.
"""
import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _parse_spec(text, mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    entries = [None if e in ("None", "none", "") else e
               for e in text.split(",")]
    return NamedSharding(mesh, PartitionSpec(*entries))


def cmd_plan(args):
    n_dev = args.src_devices + args.dst_devices
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={n_dev}")
    import numpy as np
    import jax
    from jax.sharding import Mesh

    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr

    global_config.resharding_transfer_latency_s = args.latency_ms / 1e3
    global_config.resharding_wire_bandwidth = args.bandwidth
    global_config.resharding_wire_model = args.wire_model

    devices = jax.devices()
    if len(devices) < n_dev:
        sys.exit(f"need {n_dev} devices, have {len(devices)}")
    src_mesh = Mesh(np.array(devices[:args.src_devices]), ("x",))
    dst_mesh = Mesh(np.array(devices[args.src_devices:n_dev]), ("x",))
    shape = tuple(int(s) for s in args.shape.split(","))
    itemsize = np.dtype(args.dtype).itemsize
    src = _parse_spec(args.src_spec, src_mesh)
    dst = _parse_spec(args.dst_spec, dst_mesh)

    spec = cmr.plan_resharding(shape, itemsize, src, dst)
    print(f"edge: {shape} {args.dtype} "
          f"{cmr._sharding_key(src)} -> {cmr._sharding_key(dst)}")
    print(f"wire model: {args.wire_model}  "
          f"latency={args.latency_ms}ms  bandwidth={args.bandwidth}")
    print(f"chosen strategy: {spec.strategy}"
          f"{' (from compile cache)' if spec.strategy_cached else ''}")
    print(f"planned cross-mesh bytes: {spec.transfer_bytes:.0f} "
          f"(broadcast {spec.broadcast_bytes:.0f}); "
          f"max-link {spec.max_link_bytes:.0f} B "
          f"(naive {spec.max_link_bytes_naive:.0f} B)")
    print("candidates:")
    for name, stats in spec.strategy_stats.items():
        cost = spec.strategy_costs.get(name)
        cost_s = f"{cost * 1e3:.3f}ms" if cost is not None else "n/a"
        mark = " <-- chosen" if name == spec.strategy else ""
        print(f"  {name:<22} est={cost_s:>10}  "
              f"link_msgs={stats['max_link_messages']:>3}  "
              f"link_bytes={stats['max_link_bytes']:>10.0f}  "
              f"wire_total={stats['total_bytes']:>10.0f}{mark}")
    if args.verify:
        from alpa_tpu.analysis import plan_verifier
        print("static edge verdict:")
        for line in plan_verifier.verify_edge(shape, args.dtype, src, dst,
                                              weight=args.weight):
            print(f"  {line}")
    print()
    print(cmr.format_resharding_plan())


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    pp = sub.add_parser("plan", help="plan one cross-mesh edge and "
                        "print the strategy decision")
    pp.add_argument("--shape", default="1024,1024",
                    help="global array shape, comma-separated")
    pp.add_argument("--dtype", default="float32")
    pp.add_argument("--src-devices", type=int, default=4)
    pp.add_argument("--dst-devices", type=int, default=4)
    pp.add_argument("--src-spec", default="x,None",
                    help="source PartitionSpec entries, e.g. x,None")
    pp.add_argument("--dst-spec", default="None,None")
    pp.add_argument("--latency-ms", type=float, default=2.0,
                    help="emulated per-message wire latency")
    pp.add_argument("--bandwidth", type=float, default=0.0,
                    help="emulated per-link bandwidth, bytes/s (0 = off)")
    pp.add_argument("--wire-model", default="link",
                    choices=("call", "link"))
    pp.add_argument("--verify", action="store_true",
                    help="append the static per-edge typing verdict "
                         "(plan_verifier.verify_edge)")
    pp.add_argument("--weight", action="store_true",
                    help="treat the edge as microbatch-invariant "
                         "(weight) payload for --verify")
    pp.set_defaults(fn=cmd_plan)
    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
