"""Inspect the collective resharding planner (ISSUE 7).

Usage::

    python scripts/reshard_tool.py plan --shape 1024,1024 \
        --src-devices 4 --dst-devices 4 \
        --src-spec x,None --dst-spec None,None \
        [--dtype float32] [--latency-ms 2.0] [--bandwidth 0] \
        [--wire-model link]

    python scripts/reshard_tool.py grad --shapes 1024x1024,4096x256,64 \
        --devices 8 --mode int8 [--min-bytes 65536] \
        [--num-micro-batches 4] [--no-error-feedback]

``plan`` plans one cross-mesh edge with :func:`plan_resharding` and
prints the chosen strategy, every candidate's estimated cost and
busiest-link load, and the planned wire bytes — the same per-edge
decision `dump_debug_info` records as ``resharding_plan.txt``.

``grad`` prices a list of gradient tensors through the quantized
collective cost model (ISSUE 19): per tensor it prints the full
fp32 wire bytes, the quantized wire bytes (payload + one fp32 scale
per 256-element block), the full all-reduce vs quantized
reduce-scatter cost from the live :class:`LogicalDeviceMesh` cost
model, the mode the ILP would choose under the given knobs
(``grad_eligible``), and the composed certified error bound
(``grad_error_bound``, two-hop reduce-scatter composition with the
error-feedback amortization rule applied).

Spec syntax: comma-separated PartitionSpec entries over the 1-D device
axis ``x`` (``x`` = sharded on that dim, ``None`` = replicated), e.g.
``x,None`` is a row shard.  Runs on the CPU backend with emulated
devices; the planner's tiling math is device-count-driven, so the
decisions match what the real meshes would get.
"""
import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _parse_spec(text, mesh):
    from jax.sharding import NamedSharding, PartitionSpec
    entries = [None if e in ("None", "none", "") else e
               for e in text.split(",")]
    return NamedSharding(mesh, PartitionSpec(*entries))


def cmd_plan(args):
    n_dev = args.src_devices + args.dst_devices
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "") +
        f" --xla_force_host_platform_device_count={n_dev}")
    import numpy as np
    import jax
    from jax.sharding import Mesh

    from alpa_tpu.global_env import global_config
    from alpa_tpu.pipeline_parallel import cross_mesh_resharding as cmr

    global_config.resharding_transfer_latency_s = args.latency_ms / 1e3
    global_config.resharding_wire_bandwidth = args.bandwidth
    global_config.resharding_wire_model = args.wire_model

    devices = jax.devices()
    if len(devices) < n_dev:
        sys.exit(f"need {n_dev} devices, have {len(devices)}")
    src_mesh = Mesh(np.array(devices[:args.src_devices]), ("x",))
    dst_mesh = Mesh(np.array(devices[args.src_devices:n_dev]), ("x",))
    shape = tuple(int(s) for s in args.shape.split(","))
    itemsize = np.dtype(args.dtype).itemsize
    src = _parse_spec(args.src_spec, src_mesh)
    dst = _parse_spec(args.dst_spec, dst_mesh)

    spec = cmr.plan_resharding(shape, itemsize, src, dst)
    print(f"edge: {shape} {args.dtype} "
          f"{cmr._sharding_key(src)} -> {cmr._sharding_key(dst)}")
    print(f"wire model: {args.wire_model}  "
          f"latency={args.latency_ms}ms  bandwidth={args.bandwidth}")
    print(f"chosen strategy: {spec.strategy}"
          f"{' (from compile cache)' if spec.strategy_cached else ''}")
    print(f"planned cross-mesh bytes: {spec.transfer_bytes:.0f} "
          f"(broadcast {spec.broadcast_bytes:.0f}); "
          f"max-link {spec.max_link_bytes:.0f} B "
          f"(naive {spec.max_link_bytes_naive:.0f} B)")
    print("candidates:")
    for name, stats in spec.strategy_stats.items():
        cost = spec.strategy_costs.get(name)
        cost_s = f"{cost * 1e3:.3f}ms" if cost is not None else "n/a"
        mark = " <-- chosen" if name == spec.strategy else ""
        print(f"  {name:<22} est={cost_s:>10}  "
              f"link_msgs={stats['max_link_messages']:>3}  "
              f"link_bytes={stats['max_link_bytes']:>10.0f}  "
              f"wire_total={stats['total_bytes']:>10.0f}{mark}")
    if args.verify:
        from alpa_tpu.analysis import plan_verifier
        print("static edge verdict:")
        for line in plan_verifier.verify_edge(shape, args.dtype, src, dst,
                                              weight=args.weight):
            print(f"  {line}")
    print()
    print(cmr.format_resharding_plan())


def cmd_grad(args):
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np

    from alpa_tpu.device_mesh import LogicalDeviceMesh
    from alpa_tpu.pipeline_parallel import reshard_codec as codec

    dtype = np.dtype(args.dtype)
    mesh = LogicalDeviceMesh(None, np.arange(args.devices))
    min_bytes = args.min_bytes
    ef = not args.no_error_feedback
    hops = args.num_micro_batches

    shapes = []
    for tok in args.shapes.split(","):
        shapes.append(tuple(int(s) for s in tok.split("x")))

    print(f"devices={args.devices}  mode={args.mode}  "
          f"min_bytes={min_bytes}  error_feedback={'on' if ef else 'off'}  "
          f"micro_batches={hops}")
    hdr = (f"{'shape':<16} {'bytes':>12} {'wire_bytes':>12} "
           f"{'all_reduce':>12} {'rs_quant':>12} {'chosen':>10} "
           f"{'bound':>10}")
    print(hdr)
    print("-" * len(hdr))
    total_full = total_wire = 0.0
    for shape in shapes:
        nbytes = float(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        full_cost = mesh.all_reduce_cost(nbytes, 0)
        q_cost = mesh.reduce_scatter_cost_quantized(nbytes, 0,
                                                    dtype.itemsize)
        eligible = codec.grad_eligible(shape, dtype, args.mode,
                                       min_bytes=min_bytes)
        chosen = args.mode if eligible else "full"
        wire = (codec.grad_wire_bytes(shape, dtype.itemsize, args.mode)
                if eligible else nbytes)
        bound = (codec.grad_error_bound(args.mode, reduce_scatter=True,
                                        error_feedback=ef, hops=hops)
                 if eligible else 0.0)
        total_full += nbytes
        total_wire += wire
        shape_s = "x".join(str(s) for s in shape)
        print(f"{shape_s:<16} {nbytes:>12.0f} {wire:>12.0f} "
              f"{full_cost:>12.4f} {q_cost:>12.4f} {chosen:>10} "
              f"{bound:>10.5f}")
    ratio = total_full / total_wire if total_wire else 1.0
    print("-" * len(hdr))
    print(f"total wire bytes: {total_full:.0f} -> {total_wire:.0f} "
          f"({ratio:.2f}x reduction)")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = p.add_subparsers(dest="cmd", required=True)
    pp = sub.add_parser("plan", help="plan one cross-mesh edge and "
                        "print the strategy decision")
    pp.add_argument("--shape", default="1024,1024",
                    help="global array shape, comma-separated")
    pp.add_argument("--dtype", default="float32")
    pp.add_argument("--src-devices", type=int, default=4)
    pp.add_argument("--dst-devices", type=int, default=4)
    pp.add_argument("--src-spec", default="x,None",
                    help="source PartitionSpec entries, e.g. x,None")
    pp.add_argument("--dst-spec", default="None,None")
    pp.add_argument("--latency-ms", type=float, default=2.0,
                    help="emulated per-message wire latency")
    pp.add_argument("--bandwidth", type=float, default=0.0,
                    help="emulated per-link bandwidth, bytes/s (0 = off)")
    pp.add_argument("--wire-model", default="link",
                    choices=("call", "link"))
    pp.add_argument("--verify", action="store_true",
                    help="append the static per-edge typing verdict "
                         "(plan_verifier.verify_edge)")
    pp.add_argument("--weight", action="store_true",
                    help="treat the edge as microbatch-invariant "
                         "(weight) payload for --verify")
    pp.set_defaults(fn=cmd_plan)
    pg = sub.add_parser("grad", help="price gradient tensors through the "
                        "quantized collective cost model")
    pg.add_argument("--shapes", default="1024x1024",
                    help="comma-separated tensor shapes, dims joined "
                         "with 'x', e.g. 1024x1024,4096x256,64")
    pg.add_argument("--dtype", default="float32")
    pg.add_argument("--devices", type=int, default=8,
                    help="data-parallel group size")
    pg.add_argument("--mode", default="int8", choices=("int8", "fp8"),
                    help="gradient codec (grad_quantize knob)")
    pg.add_argument("--min-bytes", type=int, default=65536,
                    help="grad_quantize_min_bytes eligibility floor")
    pg.add_argument("--num-micro-batches", type=int, default=4,
                    help="accumulation hops for the composed bound")
    pg.add_argument("--no-error-feedback", action="store_true",
                    help="price without the error-feedback "
                         "amortization rule (bound scales with hops)")
    pg.set_defaults(fn=cmd_grad)
    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
