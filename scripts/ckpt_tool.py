"""Inspect / verify / garbage-collect a content-addressed checkpoint
store (ISSUE 3; see docs/checkpointing.md).

Usage::

    python scripts/ckpt_tool.py inspect ROOT [--step N]
    python scripts/ckpt_tool.py verify  ROOT [--step N | --all]
    python scripts/ckpt_tool.py gc      ROOT [--keep-last-k K]
                                             [--keep-every-n N]
    python scripts/ckpt_tool.py last-good ROOT
    python scripts/ckpt_tool.py stat

``inspect`` lists committed steps; with ``--step`` it prints one
step's per-leaf chunk map including the sharding layout each leaf was
saved under (per-dim piece counts from the chunk index-maps) and a
params vs optimizer-state byte summary — under ZeRO weight-update
sharding (docs/performance.md) the opt-state leaves show partitioned
layouts while params stay ``full``;
``verify`` re-hashes every chunk a step references and exits non-zero
on corruption; ``gc`` optionally applies a retention policy, then
deletes chunks no surviving manifest references (do NOT run it while a
training run is saving into the same root); ``last-good`` prints the
most recent *verified* step — newest manifest whose every chunk passes
hash verification, the exact step the elastic supervisor restores
(docs/fault_tolerance.md#elastic-training) — and exits non-zero when
no step verifies, so shell runbooks and the supervisor share one
source of truth; ``stat`` prints the process-global checkpoint
counters.
"""
import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from alpa_tpu.checkpoint.policy import RetentionPolicy  # noqa: E402
from alpa_tpu.checkpoint.store import (CheckpointNotFoundError,  # noqa: E402
                                       ShardStore)


def _store(args) -> ShardStore:
    if not os.path.isdir(os.path.join(args.root, "manifests")):
        sys.exit(f"{args.root} is not a checkpoint store "
                 "(no manifests/ directory)")
    return ShardStore(args.root)


def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB", "TB"):
        if n < 1024 or unit == "TB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n}B"


def _leaf_layout(leaf) -> str:
    """Sharding layout recorded at save time, read off the chunk
    index-maps: per-dim piece counts (``4x1`` = dim 0 cut in 4), or
    ``full`` when one extent covers the whole leaf.  Chunk-size
    splitting only subdivides dim 0 of an existing piece, so counts > 1
    on later dims always mean a real partitioned save."""
    shape = tuple(leaf["shape"])
    idxs = [e.get("index") for e in leaf["chunks"]]
    if not shape or not idxs or any(not ix for ix in idxs):
        return "full"
    cuts = [len({(int(a), int(b)) for ix in idxs
                 for a, b in [ix[d]]}) for d in range(len(shape))]
    if all(c == 1 for c in cuts):
        return "full"
    return "x".join(str(c) for c in cuts)


def cmd_inspect(args):
    from alpa_tpu.shard_parallel.auto_sharding import (is_opt_state_path,
                                                      path_components)
    store = _store(args)
    if args.step is not None:
        manifest = store.read_manifest(args.step)
        print(f"step {manifest['step']}  "
              f"plan={str(manifest.get('plan_fingerprint'))[:16]}  "
              f"meta={manifest.get('meta')}")
        print(f"{'leaf':<40} {'shape':<18} {'dtype':<10} "
              f"{'chunks':>6} {'layout':>8} {'bytes':>10}")
        totals = {}  # group -> [n_leaves, n_sharded, bytes]
        for name, leaf in sorted(manifest["leaves"].items()):
            nbytes = sum(e["nbytes"] for e in leaf["chunks"])
            layout = _leaf_layout(leaf)
            print(f"{name:<40} {str(tuple(leaf['shape'])):<18} "
                  f"{leaf['dtype']:<10} {len(leaf['chunks']):>6} "
                  f"{layout:>8} {_fmt_bytes(nbytes):>10}")
            if is_opt_state_path(name):
                group = "opt_state"
            elif "params" in path_components(name):
                group = "params"
            else:
                group = "other"
            t = totals.setdefault(group, [0, 0, 0])
            t[0] += 1
            t[1] += layout != "full"
            t[2] += nbytes
        print()
        for group in ("params", "opt_state", "other"):
            if group not in totals:
                continue
            n, n_sharded, nbytes = totals[group]
            print(f"{group:<10} {n:>4} leaves  {_fmt_bytes(nbytes):>10}"
                  f"  ({n_sharded} saved in pieces)")
        if totals.get("params", [0, 0, 0])[2]:
            ratio = (totals.get("opt_state", [0, 0, 0])[2] /
                     totals["params"][2])
            print(f"opt_state / params byte ratio: {ratio:.2f}")
        return
    steps = store.all_steps()
    if not steps:
        print(f"no committed steps in {args.root}")
        return
    print(f"{'step':>12} {'leaves':>7} {'chunks':>7} {'bytes':>10}")
    for step in steps:
        manifest = store.read_manifest(step)
        n_chunks = sum(len(l["chunks"])
                       for l in manifest["leaves"].values())
        nbytes = sum(e["nbytes"] for l in manifest["leaves"].values()
                     for e in l["chunks"])
        print(f"{step:>12} {len(manifest['leaves']):>7} "
              f"{n_chunks:>7} {_fmt_bytes(nbytes):>10}")


def cmd_verify(args):
    store = _store(args)
    steps = store.all_steps() if args.all else \
        [args.step if args.step is not None else store.latest_step()]
    if steps == [None]:
        sys.exit(f"no committed steps in {args.root}")
    bad_steps = 0
    for step in steps:
        report = store.verify_step(step)
        status = "OK" if report["ok"] else \
            f"CORRUPT ({len(report['bad'])} bad chunks)"
        print(f"step {report['step']}: {status}  "
              f"({report['n_chunks']} chunks, "
              f"{_fmt_bytes(report['n_bytes'])})")
        for bad in report["bad"]:
            print(f"  leaf {bad['leaf']}: {bad['error']}")
        bad_steps += 0 if report["ok"] else 1
    if bad_steps:
        sys.exit(f"{bad_steps}/{len(steps)} steps failed verification")


def cmd_gc(args):
    store = _store(args)
    if args.keep_last_k or args.keep_every_n:
        policy = RetentionPolicy(keep_last_k=args.keep_last_k,
                                 keep_every_n=args.keep_every_n)
        doomed = policy.to_delete(store.all_steps())
        for step in doomed:
            store.delete_step(step)
        print(f"retention dropped steps {doomed or '[]'} "
              f"(surviving: {store.all_steps()})")
    result = store.gc()
    print(f"gc removed {result['chunks_removed']} chunks, "
          f"freed {_fmt_bytes(result['bytes_freed'])}")


def cmd_last_good(args):
    store = _store(args)
    step = store.last_verified_step()
    if step is None:
        sys.exit(f"no verified steps in {args.root}")
    print(step)


def cmd_stat(args):
    from alpa_tpu.monitoring import format_checkpoint_report
    print(format_checkpoint_report())


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("inspect", help="list steps / one step's leaves")
    p.add_argument("root")
    p.add_argument("--step", type=int)
    p.set_defaults(fn=cmd_inspect)

    p = sub.add_parser("verify", help="re-hash every referenced chunk")
    p.add_argument("root")
    p.add_argument("--step", type=int)
    p.add_argument("--all", action="store_true")
    p.set_defaults(fn=cmd_verify)

    p = sub.add_parser("gc", help="retention + unreferenced-chunk gc")
    p.add_argument("root")
    p.add_argument("--keep-last-k", type=int, default=0)
    p.add_argument("--keep-every-n", type=int, default=0)
    p.set_defaults(fn=cmd_gc)

    p = sub.add_parser("last-good",
                       help="print the newest hash-verified step")
    p.add_argument("root")
    p.set_defaults(fn=cmd_last_good)

    p = sub.add_parser("stat", help="process-global counters")
    p.set_defaults(fn=cmd_stat)

    args = parser.parse_args()
    try:
        args.fn(args)
    except CheckpointNotFoundError as e:
        sys.exit(str(e))


if __name__ == "__main__":
    main()
