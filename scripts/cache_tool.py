"""Inspect / clear / summarize the persistent compile cache (ISSUE 2).

The cache directory comes from ``--dir``, else ``ALPA_TPU_CACHE_DIR``.

Usage::

    python scripts/cache_tool.py inspect [--dir DIR] [--namespace NS]
    python scripts/cache_tool.py clear   [--dir DIR] [--namespace NS]
    python scripts/cache_tool.py stat    [--dir DIR]

``inspect`` lists every disk entry (namespace, key prefix, size, age);
``clear`` removes entries (optionally one namespace: ilp / stage_dp /
parallel_plan); ``stat`` prints totals per namespace.
"""
import argparse
import collections
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from alpa_tpu.compile_cache import (CompileCache,  # noqa: E402
                                    CACHE_FORMAT_VERSION, read_entry_format)


def _cache_from(args) -> CompileCache:
    cache_dir = args.dir or os.environ.get("ALPA_TPU_CACHE_DIR")
    if not cache_dir:
        sys.exit("no cache dir: pass --dir or set ALPA_TPU_CACHE_DIR")
    return CompileCache(cache_dir=cache_dir)


def _age(mtime: float) -> str:
    s = time.time() - mtime
    for unit, div in (("d", 86400), ("h", 3600), ("m", 60)):
        if s >= div:
            return f"{s / div:.1f}{unit}"
    return f"{s:.0f}s"


def cmd_inspect(args):
    cache = _cache_from(args)
    entries = [e for e in cache.entries()
               if args.namespace in (None, e["namespace"])]
    if not entries:
        print(f"no entries in {cache.cache_dir}")
        return
    print(f"{'namespace':<14} {'key':<18} {'bytes':>9} {'age':>7}")
    for e in entries:
        print(f"{e['namespace']:<14} {e['key'][:16] + '..':<18} "
              f"{e['bytes']:>9} {_age(e['mtime']):>7}")
    print(f"{len(entries)} entries, "
          f"{sum(e['bytes'] for e in entries)} bytes total")


def cmd_clear(args):
    cache = _cache_from(args)
    removed = cache.clear(namespace=args.namespace)
    what = args.namespace or "all namespaces"
    print(f"removed {removed} disk entries ({what}) from {cache.cache_dir}")


def cmd_stat(args):
    cache = _cache_from(args)
    # [count, bytes, current-format, legacy-format, unreadable]
    per_ns = collections.defaultdict(lambda: [0, 0, 0, 0, 0])
    for e in cache.entries():
        row = per_ns[e["namespace"]]
        row[0] += 1
        row[1] += e["bytes"]
        fmt = read_entry_format(e["path"])
        if fmt == CACHE_FORMAT_VERSION:
            row[2] += 1
        elif fmt is None:
            row[4] += 1
        else:
            row[3] += 1
    print(f"cache dir: {cache.cache_dir}")
    print(f"current format: v{CACHE_FORMAT_VERSION} "
          f"(dataflow-graph-aware plans)")
    if not per_ns:
        print("  (empty)")
    for ns, (n, nbytes, cur, legacy, bad) in sorted(per_ns.items()):
        extra = f"  current={cur} legacy={legacy}"
        if bad:
            extra += f" unreadable={bad}"
        print(f"  {ns:<14} {n:>5} entries  {nbytes:>10} bytes{extra}")
    legacy_total = sum(v[3] for v in per_ns.values())
    if legacy_total:
        print(f"  NOTE: {legacy_total} entries predate the dataflow-graph "
              f"format; they can never hit (keys embed the format version) "
              f"— run 'clear' to reclaim the space")


def main():
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    sub = parser.add_subparsers(dest="cmd", required=True)
    for name, fn in (("inspect", cmd_inspect), ("clear", cmd_clear),
                     ("stat", cmd_stat)):
        p = sub.add_parser(name)
        p.add_argument("--dir", default=None,
                       help="cache directory (default: $ALPA_TPU_CACHE_DIR)")
        if name != "stat":
            p.add_argument("--namespace", default=None,
                           choices=["ilp", "stage_dp", "parallel_plan"])
        p.set_defaults(fn=fn)
    args = parser.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()
