"""Profile the real TPU chip into prof_database_tpu.json (safe envelope).

Runs the measurement in a child process with a hard timeout so a wedged
relay cannot hang the caller (same guard as bench.py).  Stays inside the
known-safe shape envelope: largest dot is 4096^2 bf16 (32 MB/operand).

Usage:  PYTHONPATH=/root/repo:/root/.axon_site python scripts/profile_tpu.py
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "prof_database_tpu.json")


def inner():
    import alpa_tpu
    from alpa_tpu.device_mesh import get_global_cluster
    from alpa_tpu.mesh_profiling import profile_all

    alpa_tpu.init("local")
    db = profile_all(get_global_cluster(), OUT)
    for key, res in db.data.items():
        cal = res.fit()
        print(f"{key}: sec/flop@1e12={cal.sec_per_flop(1e12):.3e} "
              f"({1.0 / cal.sec_per_flop(1e12) / 1e12:.1f} TFLOPS)")
    print(f"saved {OUT}")


if __name__ == "__main__":
    if "--inner" in sys.argv:
        inner()
        sys.exit(0)
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--inner"],
            timeout=600)
        sys.exit(r.returncode)
    except subprocess.TimeoutExpired:
        print("TPU profiling timed out (relay wedged?); no DB written")
        sys.exit(1)
