#!/bin/bash
# Probe the TPU relay every 10 min via bench.py --probe (the single probe
# definition); append status lines.
# Usage: scripts/chip_probe.sh [logfile] [interval_s] [max_iters]
LOG=${1:-/tmp/chip_probe.log}
INTERVAL=${2:-600}
MAX=${3:-70}
HERE=$(dirname "$(dirname "$(readlink -f "$0")")")
for i in $(seq 1 "$MAX"); do
  ts=$(date -u +%FT%TZ)
  if python "$HERE/bench.py" --probe >/dev/null 2>&1; then
    echo "$ts OK" >> "$LOG"
  else
    echo "$ts WEDGED" >> "$LOG"
  fi
  sleep "$INTERVAL"
done
