"""Multi-process serving fleet recipe (ISSUE 18 satellite).

Launches N ControllerServer worker PROCESSES (each its own Python
process with its own model replica — the multi-host serving shape,
minus the hosts) plus ONE phase-aware RouterServer in this process,
wired over HTTP.  Workers share identical params (same PRNG seed), so
the fleet serves one logical model and the disaggregated handoff is
bit-exact across processes.

Usage::

    # monolithic 2-replica fleet
    python scripts/serve_fleet.py --replicas 2

    # disaggregated: 1 prefill + 2 decode workers
    python scripts/serve_fleet.py --prefill 1 --decode 2 \
        --disagg-mode auto

    # one-shot smoke: boot, run one streamed request, exit 0/1
    python scripts/serve_fleet.py --prefill 1 --decode 1 \
        --disagg-mode auto --smoke

The parent prints ``FLEET_READY router=http://127.0.0.1:PORT`` once
every worker passed ``/healthz`` and the router is serving; send it a
``POST /completions`` (``stream`` supported — SSE passes through the
router for HTTP replicas) or ``GET /healthz`` for the per-replica,
per-phase view.  Ctrl-C tears the whole fleet down.

Worker mode (internal): ``--worker --phase X`` boots one
ControllerServer on a free port, registers the tiny bench model as
``m``, and prints ``WORKER_READY port=N`` on stdout.
"""
import argparse
import json
import os
import signal
import subprocess
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MODEL = "m"


def _build_generator(seq_len: int, prefill_chunk: int):
    from alpa_tpu.model.gpt_model import GPTConfig, init_gpt_real
    from alpa_tpu.serve.generation import Generator
    cfg = GPTConfig(hidden_size=32, num_layers=2, num_heads=4,
                    seq_len=seq_len, vocab_size=64)
    # default PRNGKey(0): every worker process holds identical params
    model, params = init_gpt_real(cfg, 1)
    return Generator(model, params, cfg, prefill_chunk=prefill_chunk)


def run_worker(args) -> None:
    from alpa_tpu.global_env import global_config
    from alpa_tpu.serve.controller import Controller, ControllerServer
    global_config.kv_paged = True
    global_config.kv_prefix_reuse = True
    controller = Controller()
    controller.register_model(
        MODEL, _build_generator(args.seq_len, args.prefill_chunk))
    server = ControllerServer(controller, args.host, 0)
    server.start()
    print(f"WORKER_READY port={server.port} phase={args.phase}",
          flush=True)
    signal.sigwait({signal.SIGINT, signal.SIGTERM})
    server.shutdown()


def _spawn_worker(args, phase: str):
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           "--phase", phase, "--host", args.host,
           "--seq-len", str(args.seq_len),
           "--prefill-chunk", str(args.prefill_chunk)]
    env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
        "JAX_PLATFORMS", "cpu"))
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            env=env)
    return proc


def _await_worker(proc, timeout: float):
    deadline = time.monotonic() + timeout
    line = ""
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line and proc.poll() is not None:
            raise RuntimeError("worker exited before WORKER_READY")
        if line.startswith("WORKER_READY"):
            return int(dict(kv.split("=") for kv in
                            line.split()[1:])["port"])
    raise RuntimeError(f"worker not ready within {timeout:.0f}s "
                       f"(last: {line!r})")


def _await_healthz(base: str, timeout: float):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=2) as resp:
                if resp.status == 200:
                    return
        except Exception:  # pylint: disable=broad-except
            pass
        time.sleep(0.1)
    raise RuntimeError(f"{base} never became healthy")


def _smoke(router_base: str) -> int:
    """One streamed request through the router; 0 on success."""
    body = json.dumps({
        "model": MODEL, "prompt_ids": [5, 9, 3, 7, 1, 2, 8, 4],
        "max_new_tokens": 4, "temperature": 0.0,
        "stream": True}).encode()
    req = urllib.request.Request(
        router_base + "/completions", data=body,
        headers={"Content-Type": "application/json"})
    tokens = []
    with urllib.request.urlopen(req, timeout=60) as resp:
        for raw in resp:
            raw = raw.strip()
            if not raw.startswith(b"data:"):
                continue
            evt = json.loads(raw[len(b"data:"):])
            if evt.get("done"):
                break
            if "error" in evt:
                print(f"SMOKE_FAIL error={evt['error']}", flush=True)
                return 1
            tokens.append(evt["token"])
    ok = len(tokens) == 4
    print(f"SMOKE_{'OK' if ok else 'FAIL'} tokens={tokens}",
          flush=True)
    return 0 if ok else 1


def run_fleet(args) -> int:
    from alpa_tpu.serve.router import (HTTPReplicaHandle, Router,
                                       RouterServer)
    plan = ([("prefill", i) for i in range(args.prefill)] +
            [("decode", i) for i in range(args.decode)] +
            [("any", i) for i in range(args.replicas)])
    if not plan:
        plan = [("any", 0), ("any", 1)]
    procs = []
    try:
        procs = [(phase, i, _spawn_worker(args, phase))
                 for (phase, i) in plan]
        router = Router(disagg_mode=args.disagg_mode,
                        disagg_backpressure_depth=args.backpressure)
        for phase, i, proc in procs:
            port = _await_worker(proc, args.boot_timeout)
            base = f"http://{args.host}:{port}"
            _await_healthz(base, args.boot_timeout)
            router.add_replica(f"{phase}{i}", HTTPReplicaHandle(base),
                               phase=phase)
            print(f"worker {phase}{i} up at {base}", flush=True)
        server = RouterServer(router, host=args.host, port=args.port)
        server.start()
        base = f"http://{args.host}:{server.port}"
        print(f"FLEET_READY router={base} workers="
              f"{','.join(f'{ph}{i}' for ph, i, _ in procs)}",
              flush=True)
        if args.smoke:
            rc = _smoke(base)
            server.shutdown()
            return rc
        try:
            signal.sigwait({signal.SIGINT, signal.SIGTERM})
        except KeyboardInterrupt:
            pass
        server.shutdown()
        return 0
    finally:
        for _, _, proc in procs:
            if proc.poll() is None:
                proc.terminate()
        for _, _, proc in procs:
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--worker", action="store_true",
                    help="internal: run one controller worker process")
    ap.add_argument("--phase", default="any",
                    choices=("any", "prefill", "decode"))
    ap.add_argument("--replicas", type=int, default=0,
                    help="phase-agnostic worker count")
    ap.add_argument("--prefill", type=int, default=0,
                    help="prefill-pool worker count")
    ap.add_argument("--decode", type=int, default=0,
                    help="decode-pool worker count")
    ap.add_argument("--disagg-mode", default="auto",
                    choices=("off", "auto", "forced"))
    ap.add_argument("--backpressure", type=int, default=0,
                    help="disagg decode-pool backpressure depth")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0,
                    help="router port (0 = ephemeral)")
    ap.add_argument("--seq-len", type=int, default=64)
    ap.add_argument("--prefill-chunk", type=int, default=8)
    ap.add_argument("--boot-timeout", type=float, default=120.0)
    ap.add_argument("--smoke", action="store_true",
                    help="boot, run one streamed request, exit")
    args = ap.parse_args(argv)
    if args.worker:
        run_worker(args)
        return 0
    return run_fleet(args)


if __name__ == "__main__":
    sys.exit(main())
