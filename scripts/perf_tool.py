"""Analyze saved telemetry traces with the step perf engine
(alpa_tpu.telemetry.perf, ISSUE 9).

Usage::

    python scripts/perf_tool.py analyze      TRACE.json [--json] [--top N]
    python scripts/perf_tool.py critical-path TRACE.json [--top K]
    python scripts/perf_tool.py whatif       TRACE.json [--zero reshard]
                                             [--name SUBSTR]
    python scripts/perf_tool.py compare      A.json B.json
    python scripts/perf_tool.py drift        [TRACE.json] [--top N] [--json]
    python scripts/perf_tool.py superopt     [--dir DIR] [--all] [--json]

``superopt`` prints every accepted certified-superoptimization rewrite
decision found in the compile cache's disk tier (ISSUE 17; the engine
caches accepted rewrites under the ``superopt`` namespace at compile
time) — before/after simulated critical path and peak bytes, the
rewritten-plan fingerprint, and the admissible-candidate search log.
The cache directory comes from ``--dir``, else ``ALPA_TPU_CACHE_DIR``.
For the verdict side of a rewrite (which findings the gate compared),
``scripts/verify_tool.py verify diff`` diffs two cached verdicts with
the same ``(analysis, code)``-set semantics the acceptance gate uses.

``analyze`` prints the full :class:`StepPerfReport` (critical path,
per-mesh bubble fractions, transfer overlap, stage MFU where RUN spans
carry stage names) for the last ``pipeshard.step`` envelope in the
trace; ``critical-path`` prints just the path table; ``whatif``
re-simulates the step with an op class made free ("if this RESHARD were
free, step −X%"); ``compare`` diffs two analyzed traces metric by
metric (the interactive sibling of ``benchmark/perf_gate.py``, which
does the same against committed baselines with tolerances); ``drift``
prints the measured-cost calibration store's worst modeled-vs-measured
divergences (ISSUE 12) — pass a trace to ingest it first, or point
``ALPA_TPU_CALIBRATION_DIR`` at a persisted store.

Traces come from ``scripts/trace_tool.py record``, from
``ALPA_TPU_TRACE_DIR`` auto-saves, or from ``dump_debug_info``'s
``trace.json``.  Offline analysis has no lowered program to join
against, so dependencies are per-track order (the report says so);
in-process callers get the dataflow-graph join via
``PipeshardDriverExecutable.get_perf_report()``.
"""
import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from alpa_tpu.telemetry import perf as _perf  # noqa: E402


def _load(path):
    with open(path, encoding="utf-8") as f:
        trace = json.load(f)
    if "traceEvents" not in trace:
        sys.exit(f"{path}: not a chrome trace (no traceEvents)")
    return trace


def _report(path):
    report = _perf.report_from_trace(_load(path))
    if report is None:
        sys.exit(f"{path}: no analyzable step (no mesh-track "
                 f"instruction/transfer spans)")
    return report


def mfu_summary(tflops_per_chip):
    """Shared MFU framing for bench tooling (scripts/mfu_breakdown.py,
    bench.py): achieved TFLOPS/chip against the one peak-FLOPs source
    (``telemetry.perf`` — the ``device_peak_tflops`` knob or the
    detected generation's bf16 peak)."""
    info = _perf.peak_flops_info()
    return {
        "generation": info["generation"],
        "peak_bf16_tflops": info["peak_bf16_tflops"],
        "mfu": round(_perf.compute_mfu(tflops_per_chip,
                                       info["peak_bf16_tflops"]), 4),
    }


def attribute_legs(results):
    """Subtraction-based step-time attribution over mfu_breakdown's
    timed legs (forward / lm-head+CE / backward / optimizer)."""
    def s(leg):
        return results.get(leg, {}).get("s")

    full, fb, fwd, fh = (s("train_step"), s("fwd_bwd"), s("forward"),
                         s("forward_hidden"))
    if any(v is None for v in (full, fb, fwd, fh)):
        return {}
    return {
        "forward_body_s": round(fh, 4),
        "lm_head_ce_s": round(fwd - fh, 4),
        "backward_s": round(fb - fwd, 4),
        "optimizer_s": round(full - fb, 4),
        "total_s": round(full, 4),
    }


def cmd_analyze(args):
    report = _report(args.trace)
    if args.json:
        print(json.dumps(report.to_dict(), indent=1))
    else:
        print(report.format_text(top=args.top))


def cmd_critical_path(args):
    report = _report(args.trace)
    print(report.critical_path.format_table(top=args.top))
    by_kind = report.critical_path.by_kind()
    if by_kind:
        parts = ", ".join(f"{k} {us:.1f} us"
                          for k, us in sorted(by_kind.items()))
        print(f"path op time by kind: {parts}")


def cmd_whatif(args):
    report = _report(args.trace)
    verdict = report.whatif(args.zero, name_substr=args.name)
    print(json.dumps(verdict, indent=1))
    what = verdict["zero"]
    print(f"if every {what} op were free: step "
          f"{verdict['baseline_us']:.1f} us -> "
          f"{verdict['whatif_us']:.1f} us "
          f"(-{100.0 * verdict['saving_fraction']:.1f}%, "
          f"{verdict['n_zeroed']} ops zeroed)", file=sys.stderr)


def _flatten(d, prefix=""):
    out = {}
    for k, v in d.items():
        key = f"{prefix}.{k}" if prefix else k
        if isinstance(v, dict):
            out.update(_flatten(v, key))
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[key] = float(v)
    return out


def _metrics_from(path):
    """Flattened metrics from a chrome trace, an ``analyze --json``
    report dict, or a perf_gate baseline file."""
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if "traceEvents" in data:
        return _flatten(_report(path).to_dict())
    if "metrics" in data:            # perf_gate baseline format
        return {k: v["value"] for k, v in data["metrics"].items()
                if isinstance(v, dict) and "value" in v}
    return _flatten(data)


def cmd_compare(args):
    a = _metrics_from(args.a)
    b = _metrics_from(args.b)
    keys = sorted(set(a) & set(b))
    print(f"{'metric':<48} {'a':>12} {'b':>12} {'ratio':>8}")
    for k in keys:
        ratio = b[k] / a[k] if a[k] else float("inf") if b[k] else 1.0
        flag = "  <--" if ratio > 1.25 or ratio < 0.8 else ""
        print(f"{k:<48} {a[k]:>12.4f} {b[k]:>12.4f} "
              f"{ratio:>8.3f}{flag}")
    only_a = sorted(set(a) - set(b))
    only_b = sorted(set(b) - set(a))
    if only_a:
        print(f"only in {args.a}: {', '.join(only_a)}")
    if only_b:
        print(f"only in {args.b}: {', '.join(only_b)}")


def cmd_drift(args):
    from alpa_tpu.telemetry import calibration as _cal
    store = _cal.get_calibration_store()
    if args.trace:
        ingested = _cal.ingest_chrome_trace(_load(args.trace),
                                            store=store)
        print(f"ingested {sum(ingested.values())} samples over "
              f"{len(ingested)} signatures from {args.trace}",
              file=sys.stderr)
    if args.json:
        print(json.dumps(_cal.drift_table(store, top=args.top),
                         indent=1))
    else:
        print(_cal.format_calibration_report(store))


def cmd_superopt(args):
    from alpa_tpu.analysis import superopt as _superopt
    cache = None
    if args.dir:
        from alpa_tpu.compile_cache import CompileCache
        cache = CompileCache(cache_dir=args.dir)
    cached = _superopt.load_cached_decisions(cache)
    if not cached:
        where = args.dir or os.environ.get("ALPA_TPU_CACHE_DIR") or (
            "(memory only — set ALPA_TPU_CACHE_DIR)")
        sys.exit(f"no cached superopt decisions in {where}; accepted "
                 f"rewrites are cached at compile time when "
                 f"superopt_mode != off")
    shown = cached if args.all else cached[:1]
    if args.json:
        print(json.dumps({"schema": "alpa-superopt/v1",
                          "decisions": [{"key": e["key"],
                                         "mtime": e["mtime"],
                                         **e["decision"]}
                                        for e in shown]},
                         indent=2, sort_keys=True, default=str))
        return
    for e in shown:
        d = e["decision"]
        base_peak = sum(d.get("baseline_peak_bytes", ()))
        peak = sum(d.get("peak_bytes", ()))
        print(f"== superopt {e['key'][:16]}.. ==")
        print(f"  baseline plan: {d.get('baseline_fingerprint', '?')[:16]}"
              f"  rewritten plan: {d.get('fingerprint', '?')[:16]}")
        print(f"  simulated critical path: "
              f"{d.get('baseline_makespan_us', 0.0):.1f} -> "
              f"{d.get('makespan_us', 0.0):.1f} us")
        print(f"  simulated peak bytes:    {base_peak:.0f} -> "
              f"{peak:.0f}")
        n_rewrites = sum(1 for i, x in enumerate(d.get("layout", ()))
                         if not isinstance(x, int) or x != i)
        print(f"  non-identity layout entries: {n_rewrites}")
        for entry in d.get("log", ())[-10:]:
            print(f"    {entry.get('family', '?'):<16} makespan "
                  f"{entry.get('makespan_us', 0.0):.1f} us, peak "
                  f"{entry.get('peak_bytes', 0.0):.0f} B")
        print()
    if not args.all and len(cached) > 1:
        print(f"({len(cached) - 1} older decision(s) cached; "
              f"--all to show)")


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    pa = sub.add_parser("analyze", help="full step perf report")
    pa.add_argument("trace")
    pa.add_argument("--json", action="store_true",
                    help="machine-readable report dict")
    pa.add_argument("--top", type=int, default=10)
    pa.set_defaults(func=cmd_analyze)

    pc = sub.add_parser("critical-path",
                        help="just the measured critical path")
    pc.add_argument("trace")
    pc.add_argument("--top", type=int, default=10)
    pc.set_defaults(func=cmd_critical_path)

    pw = sub.add_parser("whatif",
                        help="re-simulate with an op class made free")
    pw.add_argument("trace")
    pw.add_argument("--zero", default="reshard",
                    choices=("reshard", "transfer", "run", "free"))
    pw.add_argument("--name", default=None,
                    help="zero ops whose name contains SUBSTR instead")
    pw.set_defaults(func=cmd_whatif)

    pp = sub.add_parser("compare",
                        help="diff two analyzed traces metric by metric")
    pp.add_argument("a")
    pp.add_argument("b")
    pp.set_defaults(func=cmd_compare)

    pd = sub.add_parser(
        "drift", help="worst modeled-vs-measured cost divergences from "
        "the calibration store (ISSUE 12)")
    pd.add_argument("trace", nargs="?", default=None,
                    help="optional chrome trace to ingest first")
    pd.add_argument("--top", type=int, default=0,
                    help="show only the N worst entries (0 = all)")
    pd.add_argument("--json", action="store_true",
                    help="machine-readable drift table")
    pd.set_defaults(func=cmd_drift)

    ps = sub.add_parser(
        "superopt", help="cached certified-superoptimization rewrite "
        "decisions: before/after simulated cost + accepted rewrite "
        "log (ISSUE 17)")
    ps.add_argument("--dir", default=None,
                    help="compile cache dir (default ALPA_TPU_CACHE_DIR)")
    ps.add_argument("--all", action="store_true",
                    help="show every cached decision, not just newest")
    ps.add_argument("--json", action="store_true",
                    help="machine-readable decisions")
    ps.set_defaults(func=cmd_superopt)

    args = p.parse_args(argv)
    args.func(args)


if __name__ == "__main__":
    main()
