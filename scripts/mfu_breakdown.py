"""Profile-backed breakdown of where the bench step's time goes
(VERDICT r4 next #2: "measured >=45% of peak OR a written
profile-backed breakdown of exactly where the remaining time goes").

Times nested sub-programs of the official bench config on the chip —
pure dominant-shape matmuls (the achievable-MXU ceiling), forward
only, forward+backward, the full train step, and the lm-head+CE leg —
each in a wedge-guarded child with a scalar-readback fence.  The
differences attribute step time to forward / backward / optimizer /
logits+CE, and the pure-matmul ceiling separates "XLA didn't reach
peak on these shapes" from "the model adds overhead".

Writes benchmark/results/mfu_breakdown.json.  The peak-FLOPs framing
and the leg attribution math live in scripts/perf_tool.py /
alpa_tpu.telemetry.perf (ISSUE 9: one MFU formula) — this script only
runs the timed legs.
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from scripts.perf_tool import attribute_legs, mfu_summary  # noqa: E402

_CHILD = r'''
import json, sys, time
sys.path.insert(0, "__REPO__")
import jax, jax.numpy as jnp, optax
import numpy as np
from alpa_tpu.model.gpt_model import GPTConfig, GPTModel
from alpa_tpu.model.model_util import gpt_lm_loss
from alpa_tpu.util import compute_gpt_tflops

leg = "__LEG__"
config = GPTConfig(hidden_size=2048, num_layers=16, num_heads=32,
                   seq_len=1024, vocab_size=51200, dtype=jnp.bfloat16,
                   attention_impl="reference", remat_blocks=True)
B = 8

def timeit(fn, *args, iters=8):
    out = fn(*args)
    jax.tree_util.tree_map(lambda x: None, out)
    # scalar D2H readback is the only real fence on the relay
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0].astype(jnp.float32))
          if hasattr(jax.tree_util.tree_leaves(out)[0], 'astype')
          else 0.0)
    tic = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(jnp.sum(jax.tree_util.tree_leaves(out)[0]
                  .astype(jnp.float32)))
    return (time.perf_counter() - tic) / iters

if leg == "matmul_ceiling":
    # the model's dominant shapes: qkv (2048x6144), mlp (2048x8192 and
    # 8192x2048), attention batch dots; all bf16
    tokens = B * config.seq_len
    x = jnp.ones((tokens, 2048), jnp.bfloat16)
    w1 = jnp.ones((2048, 8192), jnp.bfloat16)
    w2 = jnp.ones((8192, 2048), jnp.bfloat16)

    @jax.jit
    def mm(x):
        for _ in range(8):
            x = (x @ w1) @ w2
        return x

    t = timeit(mm, x)
    flops = 8 * 2 * (tokens * 2048 * 8192 + tokens * 8192 * 2048)
    print(json.dumps({"leg": leg, "s": t,
                      "tflops": flops / t / 1e12}))
    sys.exit(0)

model = GPTModel(config)
rng = jax.random.PRNGKey(0)
ids = jnp.zeros((B, config.seq_len), jnp.int32)
params = model.init(rng, ids)
batch = dict(input_ids=ids, labels=ids)
tx = optax.adam(1e-4)
opt_state = tx.init(params)

def loss_fn(p):
    return gpt_lm_loss(model.apply, p, batch)

if leg == "forward":
    f = jax.jit(loss_fn)
    t = timeit(f, params)
elif leg == "forward_hidden":
    # forward WITHOUT the lm head + CE (return_hidden mean as sink)
    @jax.jit
    def fh(p):
        h = model.apply(p, ids, return_hidden=True)
        return jnp.mean(h.astype(jnp.float32))
    t = timeit(fh, params)
elif leg == "fwd_bwd":
    g = jax.jit(lambda p: jax.value_and_grad(loss_fn)(p)[0])
    t = timeit(g, params)
elif leg == "train_step":
    @jax.jit
    def step(params, opt_state):
        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def run(p, o):
        p, o, loss = step(p, o)
        return loss
    t = timeit(run, params, opt_state)
    tfl = compute_gpt_tflops(B, config.seq_len, config.num_layers,
                             config.hidden_size, config.vocab_size, 1, t)
    print(json.dumps({"leg": leg, "s": t, "tflops_per_chip": tfl}))
    sys.exit(0)
else:
    raise SystemExit("unknown leg " + leg)
print(json.dumps({"leg": leg, "s": t}))
'''


def _child_src(leg: str) -> str:
    return _CHILD.replace("__REPO__", REPO).replace("__LEG__", leg)

LEGS = ["matmul_ceiling", "forward_hidden", "forward", "fwd_bwd",
        "train_step"]


def probe():
    try:
        return subprocess.run([sys.executable,
                               os.path.join(REPO, "bench.py"),
                               "--probe"],
                              timeout=150).returncode == 0
    except subprocess.TimeoutExpired:
        # a wedged relay usually HANGS the probe; that is a "no"
        return False


def main():
    out_path = os.path.join(REPO, "benchmark", "results",
                            "mfu_breakdown.json")
    results = {}

    def flush(attribution=None):
        """Write after EVERY leg: an outer timeout (runbook) or wedge
        mid-run must not discard completed legs."""
        peak = mfu_summary(0.0)
        report = {"config": "h2048-l16 bs8 seq1024 bf16 (official "
                            "bench)",
                  "generation": peak["generation"],
                  "peak_bf16_tflops": peak["peak_bf16_tflops"],
                  "legs": results, "attribution": attribution or {}}
        tfl = results.get("train_step", {}).get("tflops_per_chip")
        if tfl is not None:
            report["mfu"] = mfu_summary(tfl)["mfu"]
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
        return report

    for leg in LEGS:
        if not probe():
            results[leg] = {"skipped": "probe failed - stopping"}
            break
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _child_src(leg)],
                capture_output=True, text=True, timeout=600)
            line = proc.stdout.strip().splitlines()[-1] if \
                proc.stdout.strip() else "{}"
            try:
                results[leg] = json.loads(line)
            except json.JSONDecodeError:
                results[leg] = {"bad_stdout": proc.stdout[-200:],
                                "rc": proc.returncode}
            if proc.returncode != 0:
                results[leg]["rc"] = proc.returncode
                results[leg]["stderr_tail"] = proc.stderr[-300:]
        except subprocess.TimeoutExpired:
            results[leg] = {"timeout": True}
            flush()
            break
        flush()

    # subtraction-based attribution (seconds) — shared with perf_tool
    report = flush(attribute_legs(results))
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
